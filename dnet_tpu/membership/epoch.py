"""Topology epochs: the ring's fencing token.

Every solved topology the API installs gets a monotonically increasing
epoch (`EpochClock.mint`, owned by ClusterManager).  The epoch rides every
place state crosses a process boundary — the /load_model fan-out pins it
on each shard, activation frames and token callbacks carry it, reset_cache
names it — and any receiver holding a different (nonzero) epoch rejects
the message with a typed `StaleEpochError` that is COUNTED
(`dnet_stale_epoch_rejected_total{kind=}`), never computed.  Epoch 0 means
"unfenced" (pre-epoch senders, single-process adapters): a fence only
trips when BOTH sides carry a nonzero epoch and they differ, so legacy
frames and tests keep working.

`STALE_EPOCH_KINDS` / `RECOVERY_OUTCOMES` are leaf enums imported by
`dnet_tpu.obs` to pre-touch one labeled series per value (and by the
metrics lint, scripts/check_metrics_names.py pass 7, which cross-checks
both directions) — keep this module import-light so obs can pull the
enums without a cycle.
"""

from __future__ import annotations

from typing import Tuple

# Where a stale-epoch message was fenced out.  `frame` is the shard
# ingress fence (activation/relay frames), `token_cb` the API-side fence
# on shard->API token callbacks (the zombie-token hole), `reset_cache`
# the shard's control-plane fence.
STALE_EPOCH_KINDS: Tuple[str, ...] = (
    "frame",        # shard ingress rejected an activation/relay frame
    "token_cb",     # API rejected a token callback minted under an old epoch
    "reset_cache",  # shard rejected a reset RPC from a different epoch
    "fleet_route",  # fleet router fenced a dispatch to a zombie replica
)

# How a recovery round (failure re-solve or rejoin re-solve) ended.
RECOVERY_OUTCOMES: Tuple[str, ...] = (
    "recovered",    # new topology solved, reloaded, and serving
    "failed",       # reload failed after retries; previous topology restored
    "no_capacity",  # no healthy shards left / model no longer fits
)


class StaleEpochError(Exception):
    """A message minted under a topology epoch the receiver no longer
    holds.  The authoritative fence that makes re-solve safe under
    partition: a "dead" shard that was merely partitioned cannot inject
    frames/tokens/resets from the old ring into the new one."""

    def __init__(self, kind: str, held: int, got: int) -> None:
        self.kind = kind
        self.held = int(held)
        self.got = int(got)
        super().__init__(
            f"stale epoch: {kind} carries epoch {got}, holder is at "
            f"epoch {held}"
        )


def is_stale(held: int, got: int) -> bool:
    """True when a fence should trip: both sides epoch-aware, epochs
    differ.  0 on either side = unfenced (legacy sender / no topology)."""
    return bool(held) and bool(got) and int(held) != int(got)


def reject(kind: str, held: int, got: int) -> StaleEpochError:
    """Count one stale-epoch rejection and build the typed error.

    Returns (rather than raises) so ACK-shaped call sites — the shard
    ingress fence answers with a NACK message, the API token fence just
    drops — can use the same counted path as raising call sites."""
    from dnet_tpu.obs import metric  # lazy: keep this module a leaf
    from dnet_tpu.obs.events import log_event

    metric("dnet_stale_epoch_rejected_total").labels(kind=kind).inc()
    log_event("epoch_fenced", kind=kind, held=int(held), got=int(got))
    return StaleEpochError(kind, held, got)


def set_epoch_gauge(epoch: int) -> None:
    """Publish the epoch this process currently holds (API: minted; shard:
    pinned at load).  The federation scrape then shows a mixed-epoch ring
    at a glance."""
    from dnet_tpu.obs import metric

    metric("dnet_topology_epoch").set(float(epoch))


class EpochClock:
    """Monotonic epoch mint, owned by the API's ClusterManager.  One clock
    per process lifetime: every install_topology() gets a strictly larger
    epoch, so a rolled-back recovery can never reuse a fenced value."""

    def __init__(self, start: int = 0) -> None:
        self._epoch = int(start)

    @property
    def current(self) -> int:
        return self._epoch

    def mint(self) -> int:
        self._epoch += 1
        set_epoch_gauge(self._epoch)
        return self._epoch

    def observe(self, epoch: int) -> None:
        """Fast-forward past an externally seen epoch (defensive: keeps
        mint() strictly increasing even if a topology arrived with a
        larger epoch than this clock ever issued)."""
        if int(epoch) > self._epoch:
            self._epoch = int(epoch)
