"""Quarantine: fenced-out shards that stay health-probed.

Before this, a permanently lost shard was pruned from monitoring the
moment recovery re-solved without it — no path back to full capacity
short of an operator re-prepare.  The quarantine list is the path back:
the failure monitor moves a shard here when a re-solve excludes it, keeps
probing its gRPC health every tick, and (behind `DNET_REJOIN=1`) a shard
that stays green for `DNET_REJOIN_STABLE_S` seconds becomes a rejoin
candidate — re-profiled, re-solved, and delta-reloaded back into the ring
without operator action.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dnet_tpu.core.types import DeviceInfo


@dataclass
class QuarantinedShard:
    """One fenced-out shard and its probe history."""

    device: DeviceInfo
    since: float = field(default_factory=time.monotonic)
    green_since: Optional[float] = None  # first consecutive healthy probe
    probes_ok: int = 0
    last_error: str = ""

    @property
    def instance(self) -> str:
        return self.device.instance

    @property
    def addr(self) -> str:
        return f"{self.device.host}:{self.device.grpc_port}"

    def mark_green(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self.green_since is None:
            self.green_since = now
        self.probes_ok += 1
        self.last_error = ""

    def mark_red(self, error: str = "") -> None:
        self.green_since = None
        self.probes_ok = 0
        self.last_error = error

    def stable_for(self, now: Optional[float] = None) -> float:
        """Seconds of uninterrupted green probes (0 while red)."""
        if self.green_since is None:
            return 0.0
        now = time.monotonic() if now is None else now
        return max(now - self.green_since, 0.0)

    def defer(self, now: Optional[float] = None) -> None:
        """Restart the stability window (a failed/aborted rejoin attempt
        must not hot-loop: the shard re-earns its stable period)."""
        self.green_since = time.monotonic() if now is None else now


class QuarantineSet:
    """The fenced-out membership list, keyed by instance."""

    def __init__(self) -> None:
        self._shards: Dict[str, QuarantinedShard] = {}

    def __contains__(self, instance: str) -> bool:
        return instance in self._shards

    def __len__(self) -> int:
        return len(self._shards)

    def __bool__(self) -> bool:
        return bool(self._shards)

    def add(self, device: DeviceInfo) -> QuarantinedShard:
        """Quarantine a shard (idempotent: a re-quarantined shard keeps its
        original `since` but restarts its probe history — it just failed
        again)."""
        q = self._shards.get(device.instance)
        if q is None:
            q = self._shards[device.instance] = QuarantinedShard(device)
        else:
            q.device = device
            q.mark_red("re-quarantined")
        return q

    def remove(self, instance: str) -> Optional[QuarantinedShard]:
        return self._shards.pop(instance, None)

    def get(self, instance: str) -> Optional[QuarantinedShard]:
        return self._shards.get(instance)

    def instances(self) -> List[str]:
        return list(self._shards)

    def shards(self) -> List[QuarantinedShard]:
        return list(self._shards.values())

    def clear(self) -> None:
        self._shards.clear()

    def ready(
        self, stable_s: float, now: Optional[float] = None
    ) -> List[QuarantinedShard]:
        """Shards green for at least `stable_s` — rejoin candidates."""
        now = time.monotonic() if now is None else now
        return [
            q for q in self._shards.values()
            if q.green_since is not None and q.stable_for(now) >= stable_s
        ]

    def snapshot(self) -> dict:
        """Operator view for /health and the federation scrape."""
        now = time.monotonic()
        return {
            q.instance: {
                "quarantined_s": round(now - q.since, 1),
                "green_s": round(q.stable_for(now), 1),
                "probes_ok": q.probes_ok,
                "last_error": q.last_error,
            }
            for q in self._shards.values()
        }
