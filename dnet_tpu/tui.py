"""In-process Rich TUI: live log stream + model/residency + system status.

Reference: src/dnet/tui.py:21-236 — a 4-pane Live terminal layout fed by a
logging handler (banner / logs / model-info layer boxes / status+RAM).
Attach with `dnet-shard --tui` or `dnet-api --tui`.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Deque, List, Optional

import psutil
from rich.console import Console, Group
from rich.layout import Layout
from rich.live import Live
from rich.panel import Panel
from rich.table import Table
from rich.text import Text

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.obs import metric
from dnet_tpu.utils.logger import get_logger

log = get_logger()

BANNER = r"""
     _            _        _
  __| |_ __   ___| |_     | |_ _ __  _   _
 / _` | '_ \ / _ \ __|____| __| '_ \| | | |
| (_| | | | |  __/ ||_____| |_| |_) | |_| |
 \__,_|_| |_|\___|\__|     \__| .__/ \__,_|
                              |_|
"""


class TuiLogHandler(logging.Handler):
    """Appends formatted records into the TUI's bounded deque."""

    def __init__(self, sink: Deque[str]) -> None:
        super().__init__()
        self.sink = sink

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.sink.append(self.format(record))
        except Exception:
            pass


class DnetTUI:
    """Live terminal dashboard for either role."""

    def __init__(self, role: str, title: str = "dnet-tpu") -> None:
        self.role = role
        self.title = title
        self.logs: Deque[str] = deque(maxlen=200)
        self.status: dict = {"state": "starting"}
        self.model_id: Optional[str] = None
        self.layers: List[int] = []
        self.resident: List[int] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # feed thread vs render thread; instrumented under DNET_SAN=1 so
        # the render/feed lock participates in lock-order tracking
        self._lock = dsan.san_lock("DnetTUI._lock")

        self._handler = TuiLogHandler(self.logs)
        self._handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)-7s %(message)s", datefmt="%H:%M:%S")
        )
        logging.getLogger("dnet_tpu").addHandler(self._handler)

    # ---- feed ----------------------------------------------------------
    def update_status(self, **kw) -> None:
        with self._lock:
            self.status.update(kw)

    def update_model_info(
        self, model_id: Optional[str], layers: List[int], resident: Optional[List[int]] = None
    ) -> None:
        with self._lock:
            self.model_id = model_id
            self.layers = list(layers)
            self.resident = list(resident) if resident is not None else list(layers)

    # ---- render --------------------------------------------------------
    def _layer_boxes(self) -> Text:
        if not self.layers:
            return Text("no model loaded", style="dim")
        t = Text()
        resident = set(self.resident)
        for layer in self.layers:
            style = "bold green" if layer in resident else "yellow"
            t.append(f"[{layer:>3}]", style=style)
            t.append(" ")
        t.append("\n")
        t.append("green = HBM-resident, yellow = host-streamed", style="dim")
        return t

    def _render(self) -> Layout:
        layout = Layout()
        layout.split_column(
            Layout(name="top", size=8),
            Layout(name="logs"),
            Layout(name="bottom", size=6),
        )
        layout["top"].update(
            Panel(Text(BANNER, style="cyan"), title=f"{self.title} [{self.role}]")
        )
        log_text = Text("\n".join(list(self.logs)[-30:]))
        layout["logs"].update(Panel(log_text, title="logs"))

        vm = psutil.virtual_memory()
        table = Table.grid(expand=True)
        table.add_column(ratio=1)
        table.add_column(ratio=1)
        with self._lock:
            status = ", ".join(f"{k}={v}" for k, v in self.status.items())
        table.add_row(
            Group(
                Text(f"model: {self.model_id or '-'}"),
                self._layer_boxes(),
            ),
            Group(
                Text(f"status: {status}"),
                Text(
                    f"RAM {vm.used / 2**30:.1f}/{vm.total / 2**30:.1f} GiB "
                    f"({vm.percent:.0f}%)"
                ),
            ),
        )
        layout["bottom"].update(Panel(table, title="state"))
        return layout

    # ---- lifecycle -----------------------------------------------------
    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """Blocking render loop (call in a thread).

        While live, the logger's console StreamHandlers are detached — raw
        stderr writes would corrupt the alternate screen; the log pane IS
        the console for the session.
        """
        stop = stop_event or self._stop
        console = Console()
        logger = logging.getLogger("dnet_tpu")
        detached = [
            h
            for h in logger.handlers
            if isinstance(h, logging.StreamHandler)
            and not isinstance(h, (logging.FileHandler, TuiLogHandler))
        ]
        for h in detached:
            logger.removeHandler(h)
        try:
            with Live(self._render(), console=console, refresh_per_second=4, screen=True) as live:
                while not stop.is_set():
                    live.update(self._render())
                    time.sleep(0.25)
        finally:
            for h in detached:
                logger.addHandler(h)

    def start_background(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            # a second Live render loop would fight the first for the
            # alternate screen and double-detach the console handlers
            raise RuntimeError(
                "TUI render thread already running (start_background "
                "called twice without stop())"
            )
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True, name="tui")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
            if self._thread.is_alive():
                # surface the leak instead of silently abandoning the
                # render thread (it still owns the alternate screen and
                # the detached console handlers)
                metric("dnet_san_zombie_threads_total").labels(
                    thread="tui"
                ).inc()
                log.warning(
                    "TUI render thread failed to join within 2s; leaking "
                    "it as a daemon zombie (alternate screen may stay up)"
                )
            self._thread = None
        logging.getLogger("dnet_tpu").removeHandler(self._handler)
