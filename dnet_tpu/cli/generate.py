"""`dnet-generate`: offline SPMD batch generation.

The lockstep counterpart of the HTTP server: every process of a multi-host
pod runs THIS SAME command with its own DNET_MESH_PROCESS_ID, joins the
distributed runtime (parallel/mesh.ensure_distributed), builds the same
mesh engine over the global device set, and dispatches identical programs —
so the collectives line up by construction (the property request-driven
serving cannot guarantee; api/server.py refuses that combination and points
here).  Single-process it is a plain offline batch generator over the
local/mesh engine.

Input: one prompt per line (text file or - for stdin).
Output: JSONL {"prompt", "text", "tokens", "tok_s"} per line (process 0
only on multi-host pods — every process computes identical results).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from dnet_tpu.config import get_settings
from dnet_tpu.utils.logger import setup_logger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dnet-generate", description=__doc__)
    s = get_settings()
    p.add_argument("--model", required=True, help="checkpoint path or catalog id")
    p.add_argument("--prompts", default="-", help="file with one prompt per line (- = stdin)")
    p.add_argument("--output", default="-", help="JSONL output path (- = stdout)")
    p.add_argument("--max-tokens", type=int, default=128)
    p.add_argument("--max-seq", type=int, default=s.api.max_seq_len)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--param-dtype", default=s.api.param_dtype)
    p.add_argument(
        "--mesh", default="",
        help="e.g. 'pp=2,tp=2' — spans ALL hosts' chips on a joined pod",
    )
    p.add_argument("--raw", action="store_true",
                   help="feed prompts verbatim (no chat template)")
    p.add_argument(
        "--spec", type=int, default=0, metavar="L",
        help="speculative decoding lookahead (greedy only; 0 = off) — "
        "works on the local engine and on --mesh engines alike",
    )
    p.add_argument(
        "--draft", default="", metavar="MODEL",
        help="draft MODEL for speculation (checkpoint path or catalog id; "
        "needs --spec; local engine only — without it drafts come from "
        "prompt-lookup)",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_logger("api")
    s = get_settings()

    # join the pod BEFORE any backend use; each process sees the global mesh
    from dnet_tpu.parallel.mesh import ensure_distributed, parse_mesh

    dist = ensure_distributed(
        s.mesh.coordinator, s.mesh.num_processes, s.mesh.process_id
    )
    if dist and s.mesh.num_processes > 1 and args.prompts == "-":
        # stdin diverges across pod launchers (workers usually get EOF): a
        # process reading fewer prompts dispatches fewer collectives and
        # the pod deadlocks — require a shared file instead
        print(
            "multi-process pods need --prompts <file> (identical on every "
            "host); stdin is not lockstep-safe",
            file=sys.stderr,
        )
        return 2

    import jax

    from dnet_tpu.api.model_manager import resolve_model_dir
    from dnet_tpu.core.types import DecodingParams
    from dnet_tpu.utils.tokenizer import load_tokenizer

    model_dir = resolve_model_dir(args.model, s.api.models_dir)
    if model_dir is None:
        print(f"model {args.model!r} not found", file=sys.stderr)
        return 2

    draft_dir = None
    if args.draft:
        draft_dir = resolve_model_dir(args.draft, s.api.models_dir)
        if draft_dir is None:
            print(f"draft model {args.draft!r} not found", file=sys.stderr)
            return 2
        if args.spec <= 0:
            print("--draft needs --spec L", file=sys.stderr)
            return 2

    mesh_kw = parse_mesh(args.mesh)
    if mesh_kw:
        if draft_dir is not None:
            print(
                "--draft is local-engine only; mesh engines draft by "
                "prompt-lookup", file=sys.stderr,
            )
            return 2
        from dnet_tpu.parallel.engine import MeshEngine

        engine = MeshEngine(
            model_dir,
            pp=mesh_kw.get("pp", 0), tp=mesh_kw.get("tp", 1),
            dp=mesh_kw.get("dp", 1), sp=mesh_kw.get("sp", 1),
            max_seq=args.max_seq, param_dtype=args.param_dtype,
            spec_lookahead=args.spec,
        )
    else:
        from dnet_tpu.core.engine import LocalEngine

        engine = LocalEngine(
            model_dir, max_seq=args.max_seq, param_dtype=args.param_dtype,
            spec_lookahead=args.spec, draft_dir=draft_dir,
        )
    tokenizer = load_tokenizer(model_dir)
    dec = DecodingParams(
        temperature=args.temperature, top_p=args.top_p, seed=args.seed
    )
    eos = set(tokenizer.eos_token_ids)

    src = sys.stdin if args.prompts == "-" else open(args.prompts)
    prompts = [ln.rstrip("\n") for ln in src if ln.strip()]
    if src is not sys.stdin:
        src.close()

    # process 0 writes; the others compute the identical stream in lockstep
    # and must NOT open the (possibly shared) output path — a worker's
    # truncating open would discard process 0's rows
    emit = (not dist) or jax.process_index() == 0
    if not emit:
        out = sys.stdout  # never written to (emit gates every write)
    else:
        out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        for i, prompt in enumerate(prompts):
            if args.raw:
                ids = tokenizer.encode(prompt)
            else:
                text = tokenizer.apply_chat_template(
                    [{"role": "user", "content": prompt}]
                )
                ids = tokenizer.encode(text, add_bos=False)
            t0 = time.perf_counter()
            toks = [
                r.token_id
                for r in engine.generate(
                    ids, dec, max_tokens=args.max_tokens,
                    eos_token_ids=eos, nonce=f"gen{i}",
                )
            ]
            dt = time.perf_counter() - t0
            if toks and toks[-1] in eos:
                toks = toks[:-1]
            if emit:
                out.write(json.dumps({
                    "prompt": prompt,
                    "text": tokenizer.decode(toks),
                    "tokens": len(toks),
                    "tok_s": round(len(toks) / max(dt, 1e-9), 2),
                }) + "\n")
                out.flush()
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
