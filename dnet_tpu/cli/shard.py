"""`dnet-shard` entry point: a shard (worker) node.

Reference analog: src/cli/shard.py.
"""

from __future__ import annotations

import argparse
import sys

from dnet_tpu.config import get_settings
from dnet_tpu.utils.logger import setup_logger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dnet-shard", description=__doc__)
    s = get_settings()
    p.add_argument("--host", default=s.shard.host)
    p.add_argument("--http-port", type=int, default=s.shard.http_port)
    p.add_argument("--grpc-port", type=int, default=s.shard.grpc_port)
    p.add_argument("--queue-size", type=int, default=s.shard.queue_size)
    p.add_argument("--shard-name", default=s.shard.name)
    p.add_argument(
        "--discovery", choices=["udp", "none"], default="udp",
        help="announce this shard over UDP broadcast (native lib)",
    )
    p.add_argument("--udp-port", type=int, default=58899)
    p.add_argument("--udp-target", default="255.255.255.255",
                   help="announce target (loopback broadcast for single-host)")
    p.add_argument("--cluster", default="default",
                   help="cluster token scoping UDP discovery membership")
    p.add_argument("--tui", action="store_true", help="live Rich terminal dashboard")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    log = setup_logger(role="shard")
    log.info(
        "dnet-shard %s starting on %s:%d (grpc %d)",
        args.shard_name or "<unnamed>",
        args.host,
        args.http_port,
        args.grpc_port,
    )
    from dnet_tpu.shard.server import serve  # noqa: PLC0415

    serve(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
