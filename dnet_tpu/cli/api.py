"""`dnet-api` entry point: the API (head) node.

Reference analog: src/cli/api.py. Grows with the build; currently parses args
and validates config so the console script is functional from day one.
"""

from __future__ import annotations

import argparse
import sys

from dnet_tpu.config import get_settings
from dnet_tpu.utils.logger import setup_logger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dnet-api", description=__doc__)
    s = get_settings()
    p.add_argument("--host", default=s.api.host)
    p.add_argument("--http-port", type=int, default=s.api.http_port)
    p.add_argument("--grpc-port", type=int, default=s.api.grpc_port)
    p.add_argument("--hostfile", default="", help="static discovery hostfile")
    p.add_argument("--model", default="", help="model to load at startup (path or id)")
    p.add_argument("--models-dir", default="", help="override DNET_API_MODELS_DIR")
    p.add_argument(
        "--mesh",
        default="",
        help="in-slice single-program serving, e.g. 'pp=2,tp=2,sp=2' (ICI fast path; sp = sequence-parallel KV / ring attention)",
    )
    p.add_argument(
        "--discovery", choices=["udp", "none"], default="none",
        help="discover shards over UDP broadcast instead of a hostfile",
    )
    p.add_argument("--udp-port", type=int, default=58899)
    p.add_argument("--udp-target", default="255.255.255.255",
                   help="announce target (loopback broadcast for single-host)")
    p.add_argument("--cluster", default="default",
                   help="cluster token scoping UDP discovery membership")
    p.add_argument("--tui", action="store_true", help="live Rich terminal dashboard")
    p.add_argument(
        "--weight-quant-bits", type=int, default=None, choices=[0, 4, 8],
        help="int4/int8 weight-only serving (default DNET_API_WEIGHT_QUANT_BITS)",
    )
    p.add_argument(
        "--auto-recover", action="store_true",
        help="on shard failure, re-solve the ring over healthy shards and reload",
    )
    p.add_argument(
        "--batch-slots", type=int, default=None,
        help="continuous batching: N KV slots share one batched decode "
        "program (default DNET_API_BATCH_SLOTS)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    log = setup_logger(role="api")
    log.info("dnet-api starting on %s:%d (grpc %d)", args.host, args.http_port, args.grpc_port)
    from dnet_tpu.api.server import serve  # noqa: PLC0415

    serve(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
