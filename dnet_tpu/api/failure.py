"""Failure detection + elastic recovery for the ring.

The reference detects but never recovers (SURVEY.md §5: "If a shard dies
mid-request the token future times out — no re-solve, no re-route" — an
explicit gap).  This monitor closes it, and treats membership as DYNAMIC
state (dnet_tpu/membership/) rather than a one-shot solve:

- periodic gRPC HealthCheck against every shard in the active topology;
- on `fail_threshold` consecutive failures a shard is marked DOWN:
  in-flight requests FAIL FAST (their token futures resolve with an error
  instead of burning the 300 s timeout) and new requests are rejected with
  a clear 503;
- with auto_recover=True the monitor re-solves the topology over the
  remaining healthy shards (when the model still fits) and reloads the
  ring — through the DELTA path, so shards whose load parameters are
  unchanged keep their weights and only bump epoch.  Every re-solve mints
  a fresh topology epoch (ClusterManager.install_topology): the fenced-out
  shard's late frames/tokens/resets are rejected, not computed, which is
  what makes re-solve safe under partition;
- recovery is CONVERGENT: a shard that dies while a recovery is already
  reloading is picked up by the bounded-round loop (the old `_recovering`
  early-return silently dropped it), and a failed reload retries under
  the `load_model` backoff class before the previous topology is
  restored;
- fenced-out shards move to a QUARANTINE list that keeps health-probing
  them; behind DNET_REJOIN=1 a shard green for DNET_REJOIN_STABLE_S
  triggers a re-profile + re-solve through the same delta path — full
  capacity restored without operator action
  (`dnet_shard_rejoins_total`).
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from dnet_tpu.core.types import DeviceInfo
from dnet_tpu.membership import QuarantineSet
from dnet_tpu.obs import metric
from dnet_tpu.obs.events import log_event
from dnet_tpu.resilience import chaos
from dnet_tpu.resilience.policy import call_with_retry
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_RECOVERY = metric("dnet_recovery_total")
_RECOVERY_S = metric("dnet_recovery_duration_seconds")
_REJOINS = metric("dnet_shard_rejoins_total")


@dataclass
class ShardHealth:
    instance: str
    consecutive_failures: int = 0
    last_ok: float = field(default_factory=time.monotonic)
    down: bool = False


class RingFailureMonitor:
    def __init__(
        self,
        cluster_manager,
        inference_manager,
        model_manager=None,
        interval_s: float = 5.0,
        fail_threshold: int = 3,
        timeout_s: float = 3.0,
        auto_recover: bool = False,
        ring_client_factory: Optional[Callable[[str], object]] = None,
        rejoin: Optional[bool] = None,
        rejoin_stable_s: Optional[float] = None,
        recovery_max_rounds: Optional[int] = None,
    ) -> None:
        from dnet_tpu.config import get_settings
        from dnet_tpu.transport.grpc_transport import RingClient

        self.cluster = cluster_manager
        self.inference = inference_manager
        self.model_manager = model_manager
        self.interval_s = interval_s
        self.fail_threshold = fail_threshold
        self.timeout_s = timeout_s
        self.auto_recover = auto_recover
        ms = get_settings().membership
        self.rejoin_enabled = ms.rejoin if rejoin is None else bool(rejoin)
        self.rejoin_stable_s = (
            ms.rejoin_stable_s if rejoin_stable_s is None
            else float(rejoin_stable_s)
        )
        self.max_recovery_rounds = max(
            ms.recovery_max_rounds if recovery_max_rounds is None
            else int(recovery_max_rounds),
            1,
        )
        self._make_client = ring_client_factory or (lambda addr: RingClient(addr))
        self.health: Dict[str, ShardHealth] = {}
        # fenced-out shards, still probed (dnet_tpu/membership/quarantine.py)
        self.quarantine = QuarantineSet()
        self._clients: Dict[str, object] = {}  # addr -> RingClient (persistent)
        self._task: Optional[asyncio.Task] = None
        self._recovering = False
        self._jitter = random.Random()

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        """Awaited shutdown: cancel + reap the probe task and close every
        cached channel IN this loop.  (The old fire-and-forget
        ensure_future(close) leaked channels whenever the loop tore down
        before the close tasks ran.)"""
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:
                log.exception("failure monitor task died during stop")
        clients, self._clients = self._clients, {}
        # independent channel closes: one slow/broken channel must not
        # serialize the rest of shutdown behind its close handshake
        outcomes = await asyncio.gather(
            *(c.close() for c in clients.values()), return_exceptions=True
        )
        for exc in outcomes:
            if isinstance(exc, Exception):
                log.debug("channel close failed during stop: %s", exc)

    # ---- state ----------------------------------------------------------
    @property
    def degraded(self) -> bool:
        return any(h.down for h in self.health.values())

    def down_shards(self) -> List[str]:
        return [h.instance for h in self.health.values() if h.down]

    def snapshot(self) -> dict:
        return {
            h.instance: {
                "down": h.down,
                "consecutive_failures": h.consecutive_failures,
                "seconds_since_ok": round(time.monotonic() - h.last_ok, 1),
            }
            for h in self.health.values()
        }

    # ---- monitoring ------------------------------------------------------
    async def _loop(self) -> None:
        while True:
            try:
                await self._tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("failure monitor tick crashed")
            # +-10% jitter: a large ring's monitors must not probe every
            # shard in lockstep (synchronized probe bursts alias with the
            # decode cadence and can themselves trip timeouts under load)
            await asyncio.sleep(
                self.interval_s * (1.0 + self._jitter.uniform(-0.1, 0.1))
            )

    async def _tick(self) -> None:
        topo = self.cluster.current_topology
        if topo is None:
            self.health.clear()
            self.quarantine.clear()  # no topology, nothing to rejoin into
            await self._prune_clients(keep=set())
            return
        by_instance = {d.instance: d for d in topo.devices}
        # drop state (and cached channels) for shards no longer in the
        # topology — quarantined shards keep their channels: they are
        # probed below, and a rejoin reuses the same address
        for gone in set(self.health) - set(by_instance):
            del self.health[gone]
        # a shard the CURRENT topology includes is an active member again
        # (an operator re-prepare readmitted it): its quarantine entry is
        # stale and must not keep shadow-probing it
        for back in [
            i for i in self.quarantine.instances() if i in by_instance
        ]:
            self.quarantine.remove(back)
        keep = {f"{d.host}:{d.grpc_port}" for d in by_instance.values()}
        keep |= {q.addr for q in self.quarantine.shards()}
        await self._prune_clients(keep=keep)

        async def check(dev: DeviceInfo) -> None:
            h = self.health.setdefault(dev.instance, ShardHealth(dev.instance))
            addr = f"{dev.host}:{dev.grpc_port}"
            client = self._clients.get(addr)
            if client is None:
                client = self._clients[addr] = self._make_client(addr)
            try:
                # chaos point: an injected fault counts as a probe failure,
                # driving the same DOWN/recovery transitions as a real one
                await chaos.inject_async("health_check")
                await client.health_check(timeout=self.timeout_s)
                h.consecutive_failures = 0
                h.last_ok = time.monotonic()
                if h.down:
                    log.info("shard %s is back", dev.instance)
                    h.down = False
            except Exception as exc:
                h.consecutive_failures += 1
                log.warning(
                    "health check %s failed (%d/%d): %s",
                    dev.instance, h.consecutive_failures, self.fail_threshold, exc,
                )
                if not h.down and h.consecutive_failures >= self.fail_threshold:
                    h.down = True
                    await self._on_shard_down(dev.instance)

        await asyncio.gather(*(check(by_instance[i]) for i in by_instance))
        await self._probe_quarantine()

    async def _prune_clients(self, keep: set) -> None:
        stale = [
            (addr, self._clients.pop(addr))
            for addr in set(self._clients) - keep
        ]
        outcomes = await asyncio.gather(
            *(client.close() for _, client in stale), return_exceptions=True
        )
        for (addr, _), exc in zip(stale, outcomes):
            if isinstance(exc, Exception):
                log.debug("pruned channel close failed for %s: %s", addr, exc)

    # ---- failure handling -------------------------------------------------
    async def _on_shard_down(self, instance: str) -> None:
        log.error("shard %s marked DOWN", instance)
        # fail in-flight requests fast instead of letting them burn the
        # full await_token timeout (the reference's 300s, inference.py)
        adapter = self.inference.adapter
        if adapter is not None:  # topology may exist before any model load
            adapter.fail_pending(f"shard {instance} is unreachable")
        if self.auto_recover:
            if self._recovering:
                # a second failure during an in-flight recovery: the shard
                # is already marked down, and the recovery loop re-checks
                # down_shards() after each reload — deferring here (instead
                # of the old silent early-return) is what makes recovery
                # convergent
                log.warning(
                    "shard %s down during active recovery; deferred to the "
                    "convergence loop", instance,
                )
                return
            await self._recover_loop()

    # ---- recovery ---------------------------------------------------------
    async def _recover_loop(self) -> None:
        """Re-solve + reload until the surviving ring is stable, bounded by
        `max_recovery_rounds`.  Each round's outcome is counted
        (dnet_recovery_total{outcome=}) and timed."""
        if self._recovering or self.model_manager is None:
            return
        self._recovering = True
        try:
            for round_no in range(1, self.max_recovery_rounds + 1):
                model_id = self.inference.model_id
                topo = self.cluster.current_topology
                if model_id is None or topo is None:
                    return
                t0 = time.monotonic()
                try:
                    outcome = await self._recover_once(model_id, topo)
                except Exception:
                    log.exception("auto-recovery round %d crashed", round_no)
                    outcome = "failed"
                _RECOVERY.labels(outcome=outcome).inc()
                _RECOVERY_S.observe(time.monotonic() - t0)
                log_event(
                    "recovery_round", outcome=outcome, round_no=round_no,
                    duration_s=round(time.monotonic() - t0, 3),
                )
                if outcome != "recovered":
                    log.error(
                        "recovery round %d ended %s; staying degraded "
                        "(next DOWN transition re-enters)", round_no, outcome,
                    )
                    return
                # convergence: shards that died DURING the reload are
                # already marked down (their _on_shard_down deferred here)
                still_down = self.down_shards()
                if not still_down:
                    return
                log.warning(
                    "shard(s) %s went down during recovery; re-solving "
                    "(round %d/%d)",
                    still_down, round_no + 1, self.max_recovery_rounds,
                )
            log.error(
                "recovery did not converge within %d rounds; staying "
                "degraded", self.max_recovery_rounds,
            )
        finally:
            self._recovering = False

    async def _recover_once(self, model_id: str, topo) -> str:
        """One re-solve + delta reload over the currently healthy shards.
        Returns a RECOVERY_OUTCOMES value."""
        # re-profile so the solver sees real capacities (healthy_devices
        # alone returns unprofiled DeviceInfo whose zeroed hbm_bytes would
        # disable the feasibility check), and never re-include a shard
        # this monitor holds DOWN or QUARANTINED — its HTTP /health may
        # still answer 200 while its gRPC data plane is dead.
        down = set(self.down_shards())
        healthy = [
            d
            for d in await self.cluster.profile_cluster()
            if d.instance not in down and d.instance not in self.quarantine
        ]
        outcome = await self._reconfigure(healthy, model_id, topo)
        if outcome != "recovered":
            return outcome
        new_topo = self.cluster.current_topology
        log.info(
            "recovered: epoch %d over %d shard(s); quarantine now %s",
            getattr(new_topo, "epoch", 0),
            len(new_topo.assignments),
            sorted(self.quarantine.instances()) or "empty",
        )
        return "recovered"

    async def _reconfigure(self, healthy: List[DeviceInfo], model_id: str, old_topo) -> str:
        """Solve over `healthy`, install (epoch mint), and delta-reload —
        restoring `old_topo` when the reload fails after retries.  The
        shared tail of failure recovery and rejoin."""
        if not healthy:
            log.error("no healthy shards left; cannot reconfigure")
            return "no_capacity"
        unprofiled = [d.instance for d in healthy if not d.hbm_bytes]
        if unprofiled:
            log.warning(
                "reconfiguring with unprofiled shard(s) %s: "
                "memory-feasibility check degraded", unprofiled,
            )
        from dnet_tpu.api.model_manager import resolve_model_dir
        from dnet_tpu.parallel.solver import (
            model_profile_from_checkpoint,
            solve_topology,
        )

        model_dir = resolve_model_dir(model_id, self.model_manager.models_dir)
        if model_dir is None:
            log.error("model %s no longer resolvable; cannot reconfigure", model_id)
            return "no_capacity"
        # size KV the way the serving path does (seq_len + kv_bits feed
        # the solver's memory model; a bare default would mis-size KV)
        profile = model_profile_from_checkpoint(
            model_dir,
            seq_len=getattr(self.model_manager, "max_seq", 4096),
            kv_bits=old_topo.kv_bits,
            weight_quant_bits=getattr(
                self.model_manager, "weight_quant_bits", 0
            ),
        )
        try:
            new_topo = solve_topology(healthy, profile, kv_bits=old_topo.kv_bits)
        except ValueError as exc:
            log.error("re-solve failed (%s); staying as-is", exc)
            return "no_capacity"
        new_topo.model = model_id
        # install mints the next epoch — the fence against the shards this
        # solve leaves out.  If the reload fails the OLD topology (and its
        # already-minted epoch) must come back, or the dead shard would
        # drop out of monitoring and the API would accept requests against
        # a ring that never loaded.
        self._install(new_topo)
        try:
            # delta reload: unchanged shards keep weights, only bump
            # epoch; transient failures retry under the load_model class
            # (its own backoff scale) instead of silently never retrying
            await call_with_retry(
                lambda: self.model_manager.load_model(model_id, delta=True),
                method="load_model",
                retryable=lambda exc: not isinstance(exc, FileNotFoundError),
            )
        except Exception:
            log.exception(
                "reload failed after retries; restoring previous topology"
            )
            self._restore(old_topo)
            # the aborted epoch may have PARTIALLY shipped: shards that
            # already took /update_topology (or a full load) hold the new
            # epoch and would fence the restored adapter forever — fatal
            # on the rejoin path, where the ring was healthy and serving.
            # Re-ship the restored topology best-effort (delta: unchanged
            # shards just re-pin the old epoch).  On the failure path this
            # usually fails too (the old topology contains the dead
            # shard) — the ring stays degraded exactly as before.
            try:
                await self.model_manager.load_model(model_id, delta=True)
            except Exception as exc:
                log.warning(
                    "restore fan-out incomplete (%s); ring stays degraded "
                    "until the next recovery", exc,
                )
            return "failed"
        # the fence is armed (new epoch loaded everywhere): EVERY shard of
        # the old topology the new solve left out — marked down, or
        # healthy but dropped by the solver's placement (singleton merge,
        # zero layers) — moves to quarantine.  Still probed, path back via
        # rejoin; and `degraded` clears NOW (resume replays wait on it).
        placed = {a.instance for a in new_topo.assignments}
        for dev in old_topo.devices:
            if dev.instance in placed:
                continue
            self.quarantine.add(dev)
            self.health.pop(dev.instance, None)
        return "recovered"

    def _install(self, topo) -> None:
        install = getattr(self.cluster, "install_topology", None)
        if install is not None:
            install(topo)
        else:  # stub cluster managers (tests) without the epoch mint
            self.cluster.current_topology = topo

    def _restore(self, topo) -> None:
        restore = getattr(self.cluster, "restore_topology", None)
        if restore is not None:
            restore(topo)
        else:
            self.cluster.current_topology = topo

    # ---- quarantine + rejoin ---------------------------------------------
    async def _probe_quarantine(self) -> None:
        """Keep probing fenced-out shards (the path back to full capacity
        the old prune-forever behavior never had), and — behind
        DNET_REJOIN=1 — rejoin one shard per tick once it has stayed green
        for the stability window."""
        if not self.quarantine:
            return
        now = time.monotonic()

        async def probe(q) -> None:
            client = self._clients.get(q.addr)
            if client is None:
                client = self._clients[q.addr] = self._make_client(q.addr)
            try:
                await client.health_check(timeout=self.timeout_s)
                q.mark_green(now)
            except Exception as exc:
                q.mark_red(str(exc))

        await asyncio.gather(*(probe(q) for q in self.quarantine.shards()))
        if not self.rejoin_enabled or self._recovering:
            return
        ready = self.quarantine.ready(self.rejoin_stable_s)
        if ready:
            # one rejoin per tick: each is a full re-solve + reload, and a
            # burst of returning shards converges over a few ticks anyway
            await self._try_rejoin(ready[0])

    async def _try_rejoin(self, q) -> None:
        """Re-admit one stably green quarantined shard: re-profile with it
        included, re-solve, delta-reload.  Any failure (including an
        injected `rejoin` chaos fault) defers the shard to re-earn its
        stability window instead of hot-looping."""
        model_id = self.inference.model_id
        topo = self.cluster.current_topology
        if self.model_manager is None or model_id is None or topo is None:
            return
        self._recovering = True
        t0 = time.monotonic()
        outcome: Optional[str] = None
        try:
            try:
                # chaos point: an injected error aborts THIS attempt the
                # way any real rejoin failure would
                await chaos.inject_async("rejoin")
            except chaos.ChaosError as exc:
                log.warning("rejoin of %s aborted by chaos: %s", q.instance, exc)
                q.defer()
                return
            devices = await self.cluster.profile_cluster()
            if q.instance not in {d.instance for d in devices}:
                # gRPC probes green but the HTTP control plane isn't
                # discoverable/serving yet: not actually ready
                log.info(
                    "rejoin of %s deferred: not in profiled device set",
                    q.instance,
                )
                q.defer()
                return
            down = set(self.down_shards())
            healthy = [
                d
                for d in devices
                if d.instance not in down
                and (d.instance == q.instance or d.instance not in self.quarantine)
            ]
            outcome = await self._reconfigure(healthy, model_id, topo)
            new_topo = self.cluster.current_topology
            if outcome == "recovered" and new_topo.assignment_for(
                q.instance
            ) is not None:
                self.quarantine.remove(q.instance)
                _REJOINS.inc()
                log.info(
                    "shard %s rejoined: epoch %d over %d shard(s)",
                    q.instance,
                    getattr(new_topo, "epoch", 0),
                    len(new_topo.assignments),
                )
            else:
                if outcome == "recovered":
                    # the reload went through but the solver gave the
                    # candidate zero layers: NOT a rejoin — it stays
                    # quarantined (probed) and re-earns its window
                    log.warning(
                        "rejoin of %s: solver did not place it; staying "
                        "quarantined", q.instance,
                    )
                q.defer()
        except Exception:
            log.exception("rejoin of %s crashed", q.instance)
            outcome = outcome or "failed"
            q.defer()
        finally:
            if outcome is not None:
                _RECOVERY.labels(outcome=outcome).inc()
                _RECOVERY_S.observe(time.monotonic() - t0)
                log_event(
                    "recovery_round", outcome=outcome, kind="rejoin",
                    duration_s=round(time.monotonic() - t0, 3),
                )
            self._recovering = False
