"""Failure detection + recovery for the ring.

The reference detects but never recovers (SURVEY.md §5: "If a shard dies
mid-request the token future times out — no re-solve, no re-route" — an
explicit gap).  This monitor closes it:

- periodic gRPC HealthCheck against every shard in the active topology;
- on `fail_threshold` consecutive failures a shard is marked DOWN:
  in-flight requests FAIL FAST (their token futures resolve with an error
  instead of burning the 300 s timeout) and new requests are rejected with
  a clear 503;
- with auto_recover=True the monitor re-solves the topology over the
  remaining healthy shards (when the model still fits) and reloads the
  ring — elastic recovery the reference never had.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from dnet_tpu.core.types import DeviceInfo
from dnet_tpu.resilience import chaos
from dnet_tpu.utils.logger import get_logger

log = get_logger()


@dataclass
class ShardHealth:
    instance: str
    consecutive_failures: int = 0
    last_ok: float = field(default_factory=time.monotonic)
    down: bool = False


class RingFailureMonitor:
    def __init__(
        self,
        cluster_manager,
        inference_manager,
        model_manager=None,
        interval_s: float = 5.0,
        fail_threshold: int = 3,
        timeout_s: float = 3.0,
        auto_recover: bool = False,
        ring_client_factory: Optional[Callable[[str], object]] = None,
    ) -> None:
        from dnet_tpu.transport.grpc_transport import RingClient

        self.cluster = cluster_manager
        self.inference = inference_manager
        self.model_manager = model_manager
        self.interval_s = interval_s
        self.fail_threshold = fail_threshold
        self.timeout_s = timeout_s
        self.auto_recover = auto_recover
        self._make_client = ring_client_factory or (lambda addr: RingClient(addr))
        self.health: Dict[str, ShardHealth] = {}
        self._clients: Dict[str, object] = {}  # addr -> RingClient (persistent)
        self._task: Optional[asyncio.Task] = None
        self._recovering = False
        self._jitter = random.Random()

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        """Awaited shutdown: cancel + reap the probe task and close every
        cached channel IN this loop.  (The old fire-and-forget
        ensure_future(close) leaked channels whenever the loop tore down
        before the close tasks ran.)"""
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:
                log.exception("failure monitor task died during stop")
        clients, self._clients = self._clients, {}
        for c in clients.values():
            try:
                await c.close()
            except Exception:
                pass

    # ---- state ----------------------------------------------------------
    @property
    def degraded(self) -> bool:
        return any(h.down for h in self.health.values())

    def down_shards(self) -> List[str]:
        return [h.instance for h in self.health.values() if h.down]

    def snapshot(self) -> dict:
        return {
            h.instance: {
                "down": h.down,
                "consecutive_failures": h.consecutive_failures,
                "seconds_since_ok": round(time.monotonic() - h.last_ok, 1),
            }
            for h in self.health.values()
        }

    # ---- monitoring ------------------------------------------------------
    async def _loop(self) -> None:
        while True:
            try:
                await self._tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("failure monitor tick crashed")
            # +-10% jitter: a large ring's monitors must not probe every
            # shard in lockstep (synchronized probe bursts alias with the
            # decode cadence and can themselves trip timeouts under load)
            await asyncio.sleep(
                self.interval_s * (1.0 + self._jitter.uniform(-0.1, 0.1))
            )

    async def _tick(self) -> None:
        topo = self.cluster.current_topology
        if topo is None:
            self.health.clear()
            await self._prune_clients(keep=set())
            return
        by_instance = {d.instance: d for d in topo.devices}
        # drop state (and cached channels) for shards no longer in the topology
        for gone in set(self.health) - set(by_instance):
            del self.health[gone]
        keep = {f"{d.host}:{d.grpc_port}" for d in by_instance.values()}
        await self._prune_clients(keep=keep)

        async def check(dev: DeviceInfo) -> None:
            h = self.health.setdefault(dev.instance, ShardHealth(dev.instance))
            addr = f"{dev.host}:{dev.grpc_port}"
            client = self._clients.get(addr)
            if client is None:
                client = self._clients[addr] = self._make_client(addr)
            try:
                # chaos point: an injected fault counts as a probe failure,
                # driving the same DOWN/recovery transitions as a real one
                await chaos.inject_async("health_check")
                await client.health_check(timeout=self.timeout_s)
                h.consecutive_failures = 0
                h.last_ok = time.monotonic()
                if h.down:
                    log.info("shard %s is back", dev.instance)
                    h.down = False
            except Exception as exc:
                h.consecutive_failures += 1
                log.warning(
                    "health check %s failed (%d/%d): %s",
                    dev.instance, h.consecutive_failures, self.fail_threshold, exc,
                )
                if not h.down and h.consecutive_failures >= self.fail_threshold:
                    h.down = True
                    await self._on_shard_down(dev.instance)

        await asyncio.gather(*(check(by_instance[i]) for i in by_instance))

    async def _prune_clients(self, keep: set) -> None:
        for addr in set(self._clients) - keep:
            client = self._clients.pop(addr)
            try:
                await client.close()
            except Exception:
                pass

    # ---- failure handling -------------------------------------------------
    async def _on_shard_down(self, instance: str) -> None:
        log.error("shard %s marked DOWN", instance)
        # fail in-flight requests fast instead of letting them burn the
        # full await_token timeout (the reference's 300s, inference.py)
        adapter = self.inference.adapter
        if adapter is not None:  # topology may exist before any model load
            adapter.fail_pending(f"shard {instance} is unreachable")
        if self.auto_recover:
            await self._try_recover()

    async def _try_recover(self) -> None:
        """Re-solve over the remaining healthy shards and reload the ring."""
        if self._recovering or self.model_manager is None:
            return
        model_id = self.inference.model_id
        topo = self.cluster.current_topology
        if model_id is None or topo is None:
            return
        self._recovering = True
        try:
            # re-profile so the solver sees real capacities (healthy_devices
            # alone returns unprofiled DeviceInfo whose zeroed hbm_bytes would
            # disable the feasibility check), and never re-include a shard
            # this monitor holds DOWN — its HTTP /health may still answer 200
            # while its gRPC data plane is dead.
            down = set(self.down_shards())
            healthy = [
                d
                for d in await self.cluster.profile_cluster()
                if d.instance not in down
            ]
            if not healthy:
                log.error("no healthy shards left; cannot recover")
                return
            unprofiled = [d.instance for d in healthy if not d.hbm_bytes]
            if unprofiled:
                log.warning(
                    "recovering with unprofiled shard(s) %s: memory-feasibility "
                    "check degraded", unprofiled,
                )
            from dnet_tpu.api.model_manager import resolve_model_dir
            from dnet_tpu.parallel.solver import (
                model_profile_from_checkpoint,
                solve_topology,
            )

            model_dir = resolve_model_dir(model_id, self.model_manager.models_dir)
            if model_dir is None:
                return
            # size KV the way the serving path does (seq_len + kv_bits feed
            # the solver's memory model; a bare default would mis-size KV)
            profile = model_profile_from_checkpoint(
                model_dir,
                seq_len=getattr(self.model_manager, "max_seq", 4096),
                kv_bits=topo.kv_bits,
                weight_quant_bits=getattr(
                    self.model_manager, "weight_quant_bits", 0
                ),
            )
            try:
                new_topo = solve_topology(healthy, profile, kv_bits=topo.kv_bits)
            except ValueError as exc:
                log.error("re-solve failed (%s); staying degraded", exc)
                return
            new_topo.model = model_id
            # install the new topology only for the duration of the reload:
            # if the reload fails the old (degraded) topology must come back,
            # or the dead shard would drop out of monitoring and the API
            # would accept requests against a ring that never loaded
            self.cluster.current_topology = new_topo
            try:
                await self.model_manager.load_model(model_id)
            except Exception:
                self.cluster.current_topology = topo
                raise
            log.info(
                "recovered: ring re-solved over %d shard(s)", len(new_topo.assignments)
            )
        except Exception:
            log.exception("auto-recovery failed")
        finally:
            self._recovering = False
