"""API-node process wiring: managers + HTTP (+ gRPC in ring mode).

Reference: src/cli/api.py:42-166.
"""

from __future__ import annotations

import asyncio
import signal

from dnet_tpu.api.http import ApiHTTPServer
from dnet_tpu.api.inference import InferenceManager
from dnet_tpu.api.model_manager import LocalModelManager
from dnet_tpu.config import get_settings
from dnet_tpu.parallel.mesh import parse_mesh as _parse_mesh
from dnet_tpu.utils.logger import get_logger

log = get_logger()


async def serve_async(args) -> None:
    s = get_settings()
    # runtime sanitizer (DNET_SAN=1): loop-stall watchdog + task audit
    # over the whole serving lifetime; install() is a no-op (None) when
    # dsan is off
    from dnet_tpu.analysis.runtime import serving as dsan_serving

    san = dsan_serving.install(asyncio.get_running_loop())
    # fail fast on a malformed DNET_CHAOS (and bannerize an armed one)
    # before the server takes traffic — never mid-request
    from dnet_tpu.resilience.chaos import validate_startup

    validate_startup(role="api")
    wq = getattr(args, "weight_quant_bits", None)
    weight_quant_bits = s.api.weight_quant_bits if wq is None else wq
    batch_slots = getattr(args, "batch_slots", None) or s.api.batch_slots
    # with continuous batching, admission must not exceed the slot pool —
    # an over-admitted request would hard-fail on prefill instead of queueing
    max_concurrent = (
        min(s.api.max_concurrent_requests, batch_slots)
        if batch_slots > 1
        else s.api.max_concurrent_requests
    )
    inference = InferenceManager(
        adapter=None,
        request_timeout_s=s.api.request_timeout_s,
        max_concurrent=max_concurrent,
    )
    # Multi-process meshes are multi-CONTROLLER: every process must dispatch
    # the same programs in lockstep, which a request-driven HTTP server
    # cannot guarantee (a request arriving at one host would dispatch a
    # collective the others never enter).  Request-driven multi-host serving
    # is the gRPC shard ring (one dnet-shard per host); the distributed
    # join is for SPMD batch/offline execution (parallel/mesh.py).
    if s.mesh.num_processes > 1:
        raise SystemExit(
            "DNET_MESH_NUM_PROCESSES>1 with the HTTP API server would "
            "deadlock on the first request (multi-controller mesh, single "
            "dispatching host). Serve multi-host via the gRPC ring: run "
            "dnet-shard on every host and dnet-api with --hostfile/UDP "
            "discovery."
        )
    from dnet_tpu.parallel.mesh import ensure_distributed

    if ensure_distributed(s.mesh.coordinator, s.mesh.num_processes, s.mesh.process_id):
        log.info(
            "joined single-process distributed runtime (coordinator %s)",
            s.mesh.coordinator,
        )
    env_mesh = {"pp": s.mesh.pp, "tp": s.mesh.tp, "dp": s.mesh.dp, "sp": s.mesh.sp}
    env_mesh_active = s.mesh.pp > 0 or s.mesh.tp > 1 or s.mesh.dp > 1 or s.mesh.sp > 1
    mesh = _parse_mesh(getattr(args, "mesh", "")) or (
        env_mesh if env_mesh_active else None
    )
    model_manager = LocalModelManager(
        inference,
        models_dir=getattr(args, "models_dir", "") or s.api.models_dir,
        max_seq=s.api.max_seq_len,
        param_dtype=s.api.param_dtype,
        mesh=mesh,
        weight_quant_bits=weight_quant_bits,
        weight_quant_group=s.api.weight_quant_group,
        kv_bits=s.kv.bits,
        batch_slots=batch_slots,
        prefix_cache=s.api.prefix_cache,
        spec_lookahead=s.api.spec_lookahead,
    )

    cluster_manager = None
    grpc_server = None
    ring_discovery = None
    if getattr(args, "discovery", "none") == "udp" and not getattr(args, "hostfile", ""):
        from dnet_tpu.utils.p2p import UdpDiscovery

        ring_discovery = UdpDiscovery(
            "api", args.http_port, args.grpc_port, is_manager=True,
            udp_port=getattr(args, "udp_port", 58899),
            target_addr=getattr(args, "udp_target", "255.255.255.255"),
            cluster=getattr(args, "cluster", "default"),
        )
        log.info("UDP discovery active (manager)")
    if getattr(args, "hostfile", "") or ring_discovery is not None:
        from dnet_tpu.api.cluster import ClusterManager
        from dnet_tpu.api.ring import ApiTokenServicer
        from dnet_tpu.api.ring_manager import RingModelManager
        from dnet_tpu.transport.grpc_transport import (
            api_service_handlers,
            start_grpc_server,
        )
        from dnet_tpu.utils.hostfile import StaticDiscovery

        discovery = (
            ring_discovery
            if ring_discovery is not None
            else StaticDiscovery.from_hostfile(args.hostfile)
        )
        cluster_manager = ClusterManager(discovery)
        # callback address shards dial for SendToken: explicit override, else
        # the interface facing the shards (reference http_api.py:188-196)
        from dnet_tpu.utils.network import primary_ip

        callback_addr = s.api.callback_addr or (
            f"{primary_ip(d.host for d in discovery.peers())}:{args.grpc_port}"
        )
        model_manager = RingModelManager(
            inference,
            cluster_manager,
            models_dir=getattr(args, "models_dir", "") or s.api.models_dir,
            api_callback_addr=callback_addr,
            max_seq=s.api.max_seq_len,
            param_dtype=s.api.param_dtype,
            weight_quant_bits=weight_quant_bits,
        )
        # token-callback receiver: shards resolve decode futures through here
        grpc_server = await start_grpc_server(
            args.host,
            args.grpc_port,
            api_service_handlers(
                ApiTokenServicer(
                    lambda r: inference.adapter.resolve_token(r)
                    if inference.adapter is not None
                    else log.warning("token for %s before model load", r.nonce)
                )
            ),
        )
        log.info(
            "ring mode: %d shard(s) via %s",
            len(discovery.peers()),
            "udp discovery" if ring_discovery is not None else "hostfile",
        )
        # failure detection + optional elastic recovery (the reference only
        # detects — SURVEY.md §5 flags the missing recovery as a gap)
        from dnet_tpu.api.failure import RingFailureMonitor

        monitor = RingFailureMonitor(
            cluster_manager,
            inference,
            model_manager=model_manager,
            interval_s=s.api.health_interval_s,
            fail_threshold=s.api.health_fail_threshold,
            auto_recover=getattr(args, "auto_recover", False),
        )
        inference.failure_monitor = monitor
        monitor.start()

    fleet = None
    if s.fleet.fleet > 1:
        # DNET_FLEET=N: the front door routes across N replicas.  The
        # stack built above becomes replica r0; additional replicas are
        # attached programmatically (the in-process ring harness /
        # bench_serve --fleet is the supported multi-replica deployment —
        # one OS process per extra ring is future work).  Unset/1 never
        # constructs the fleet layer: the single-ring path is untouched.
        from dnet_tpu.fleet import FleetManager

        fleet = FleetManager()
        fleet.add_replica("r0", inference)
        log.info(
            "fleet mode: DNET_FLEET=%d, primary registered as r0 "
            "(attach more replicas via FleetManager.add_replica)",
            s.fleet.fleet,
        )
    http = ApiHTTPServer(inference, model_manager, cluster_manager, fleet=fleet)
    await http.start(args.host, args.http_port)

    preload = getattr(args, "model", "") or ""
    if preload:
        try:
            await model_manager.load_model(preload)
        except Exception:
            # ring mode has no topology until the operator prepares one; a
            # failed preload must not kill the server
            log.exception("preload of %s failed; continuing without a model", preload)

    tui = None
    tui_task = None
    if getattr(args, "tui", False):
        from dnet_tpu.tui import DnetTUI

        tui = DnetTUI(role="api")
        tui.start_background()

        async def _feed_tui() -> None:
            while True:
                topo = getattr(cluster_manager, "current_topology", None)
                tui.update_status(
                    state="ready" if inference.ready else "no model",
                    mode="ring" if cluster_manager else ("mesh" if mesh else "local"),
                    shards=len(topo.assignments) if topo else 0,
                )
                if topo is not None:
                    layers = [l for a in topo.assignments for l in a.layers]
                else:
                    engine = getattr(model_manager, "engine", None)
                    layers = list(engine.model.layers) if engine is not None else []
                tui.update_model_info(inference.model_id, sorted(layers))
                await asyncio.sleep(1.0)

        tui_task = asyncio.ensure_future(_feed_tui())

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    log.info("dnet-api ready")
    await stop.wait()
    # graceful drain (SIGTERM/SIGINT): flip admission into drain mode —
    # /health reports "draining", new decode requests get 503 +
    # Retry-After, queued waiters shed — while the HTTP server stays up
    # so in-flight streams can finish, bounded by DNET_DRAIN_DEADLINE_S.
    # Only then do adapters/transports tear down.
    drain_s = s.admission.drain_deadline_s
    log.info(
        "shutdown signal: draining %d in-flight request(s) (bounded %.1fs)",
        inference.admission.active, drain_s,
    )
    inference.admission.begin_drain()
    if await inference.admission.wait_drained(drain_s):
        log.info("drain complete; shutting down")
    else:
        log.warning("drain deadline hit; shutting down with work in flight")
    if inference.failure_monitor is not None:
        await inference.failure_monitor.stop()
    if tui_task is not None:
        tui_task.cancel()
    if tui is not None:
        tui.stop()
    if ring_discovery is not None:
        ring_discovery.stop()
    await http.stop()
    if grpc_server is not None:
        await grpc_server.stop(grace=2)
    if inference.adapter is not None:
        await inference.adapter.shutdown()
    if san is not None:
        san.teardown(log)




def serve(args) -> None:
    asyncio.run(serve_async(args))
