"""API-node process wiring: managers + HTTP (+ gRPC in ring mode).

Reference: src/cli/api.py:42-166.
"""

from __future__ import annotations

import asyncio
import signal

from dnet_tpu.api.http import ApiHTTPServer
from dnet_tpu.api.inference import InferenceManager
from dnet_tpu.api.model_manager import LocalModelManager
from dnet_tpu.config import get_settings
from dnet_tpu.utils.logger import get_logger

log = get_logger()


async def serve_async(args) -> None:
    s = get_settings()
    inference = InferenceManager(
        adapter=None,
        request_timeout_s=s.api.request_timeout_s,
        max_concurrent=s.api.max_concurrent_requests,
    )
    model_manager = LocalModelManager(
        inference,
        models_dir=getattr(args, "models_dir", "") or s.api.models_dir,
        max_seq=s.api.max_seq_len,
        param_dtype=s.api.param_dtype,
    )

    cluster_manager = None
    if getattr(args, "hostfile", ""):
        from dnet_tpu.api.cluster import ClusterManager
        from dnet_tpu.utils.hostfile import StaticDiscovery

        discovery = StaticDiscovery.from_hostfile(args.hostfile)
        cluster_manager = ClusterManager(discovery)
        log.info("ring mode: %d shard(s) from hostfile", len(discovery.peers()))

    http = ApiHTTPServer(inference, model_manager, cluster_manager)
    await http.start(args.host, args.http_port)

    preload = getattr(args, "model", "") or ""
    if preload:
        await model_manager.load_model(preload)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    log.info("dnet-api ready")
    await stop.wait()
    log.info("shutting down")
    await http.stop()
    if inference.adapter is not None:
        await inference.adapter.shutdown()


def serve(args) -> None:
    asyncio.run(serve_async(args))
