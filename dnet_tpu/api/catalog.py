"""Supported-model catalog.

Reference: src/dnet/api/catalog.py:4-184 — a hardcoded list with arch/quant
metadata and `ci_test` flags driving the integration matrix.  On TPU the
quant story differs (bf16 native; int8/int4 weight-only to come), so entries
carry the checkpoint dtype expectations instead of MLX quant names.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass(frozen=True)
class CatalogEntry:
    id: str  # HF-style repo id or short name
    arch: str  # model_type
    params_b: float  # billions of parameters
    n_layers: int
    ci_test: bool = False
    notes: str = ""
    # weight-only serving quantizations this entry supports (reference
    # enumerates per-model quant variants as separate aliases,
    # src/dnet/api/catalog.py; on TPU a variant is the same bf16 checkpoint
    # served with ops/quant int8/int4 weights)
    quant_variants: tuple = ("int8", "int4")


QUANT_BITS = {"bf16": 0, "int8": 8, "int4": 4}


model_catalog: List[CatalogEntry] = [
    # Llama family (reference catalog: Llama 3.x 3B-70B, Hermes 70B/405B)
    CatalogEntry("meta-llama/Llama-3.2-1B-Instruct", "llama", 1.2, 16, ci_test=True),
    CatalogEntry("meta-llama/Llama-3.2-3B-Instruct", "llama", 3.2, 28, ci_test=True),
    CatalogEntry("meta-llama/Llama-3.1-8B-Instruct", "llama", 8.0, 32),
    CatalogEntry("meta-llama/Llama-3.3-70B-Instruct", "llama", 70.6, 80),
    CatalogEntry("NousResearch/Hermes-3-Llama-3.1-70B", "llama", 70.6, 80),
    CatalogEntry("NousResearch/Hermes-3-Llama-3.1-405B", "llama", 405.0, 126),
    # Qwen2.5 family (BASELINE config 3; biased-qkv llama arch)
    CatalogEntry("Qwen/Qwen2.5-7B-Instruct", "qwen2", 7.6, 28),
    CatalogEntry("Qwen/Qwen2.5-32B-Instruct", "qwen2", 32.8, 64),
    CatalogEntry("Qwen/Qwen2.5-72B-Instruct", "qwen2", 72.7, 80),
    # Qwen3 family (4B-32B in reference catalog)
    CatalogEntry("Qwen/Qwen3-4B", "qwen3", 4.0, 36, ci_test=True),
    CatalogEntry("Qwen/Qwen3-8B", "qwen3", 8.2, 36),
    CatalogEntry("Qwen/Qwen3-14B", "qwen3", 14.8, 40),
    CatalogEntry("Qwen/Qwen3-32B", "qwen3", 32.8, 64),
    CatalogEntry("Qwen/Qwen3-30B-A3B", "qwen3_moe", 30.5, 48, notes="MoE 128x top-8"),
    CatalogEntry("Qwen/Qwen3-235B-A22B", "qwen3_moe", 235.0, 94, notes="MoE 128x top-8"),
    # GPT-OSS MoE (20B/120B in reference catalog)
    CatalogEntry("openai/gpt-oss-20b", "gpt_oss", 20.9, 24, notes="MoE 32x, SWA alternating"),
    CatalogEntry("openai/gpt-oss-120b", "gpt_oss", 116.8, 36, notes="MoE 128x, SWA alternating"),
    CatalogEntry("meta-llama/Llama-3.1-70B-Instruct", "llama", 70.6, 80),
    # DeepSeek-V2 arch (MLA)
    CatalogEntry("deepseek-ai/DeepSeek-V2-Lite-Chat", "deepseek_v2", 15.7, 27, notes="MLA"),
    # Mixtral sparse MoE (BASELINE config 4)
    CatalogEntry("mistralai/Mixtral-8x7B-Instruct-v0.1", "mixtral", 46.7, 32, notes="MoE 8x top-2"),
    CatalogEntry("mistralai/Mixtral-8x22B-Instruct-v0.1", "mixtral", 141.0, 56, notes="MoE 8x top-2"),
]


def expanded_catalog() -> List[CatalogEntry]:
    """One row per (model, quant variant) — the reference enumerates each
    quant variant as its own catalog entry (src/dnet/api/catalog.py:4-175,
    e.g. Qwen3-4B-MLX-{bf16,8bit,4bit}); here a variant is the same bf16
    checkpoint served through ops/quant, addressed as `<id>:<variant>`
    (resolve_variant).  The base id (implicit bf16) is listed too."""
    out: List[CatalogEntry] = []
    for e in model_catalog:
        out.append(e)
        for v in e.quant_variants:
            out.append(
                CatalogEntry(
                    f"{e.id}:{v}", e.arch, e.params_b, e.n_layers,
                    ci_test=False,
                    notes=(e.notes + " " if e.notes else "") + f"{v} weights",
                    quant_variants=(),
                )
            )
    return out


def split_variant(model_id: str) -> tuple:
    """`<model>[:<quant>]` -> (base_id, weight_quant_bits | None).

    Catalog-independent so `:int8` also works on local checkpoint dirs;
    unknown suffixes are treated as part of the id (returns (id, None))."""
    base, sep, variant = model_id.rpartition(":")
    if sep and variant in QUANT_BITS:
        return base, QUANT_BITS[variant]
    return model_id, None


def find_entry(model_id: str) -> Optional[CatalogEntry]:
    for e in model_catalog:
        if e.id == model_id or e.id.split("/")[-1] == model_id:
            return e
    return None


def resolve_variant(model_id: str) -> Optional[tuple]:
    """Resolve `<model>[:<quant>]` aliases (reference-style quant variants):
    "Llama-3.2-1B-Instruct:int8" -> (entry, 8).  Returns (entry,
    weight_quant_bits) or None when unknown."""
    base, _, variant = model_id.partition(":")
    e = find_entry(base)
    if e is None:
        return None
    if not variant:
        return e, 0
    if variant not in QUANT_BITS:
        return None
    if variant != "bf16" and variant not in e.quant_variants:
        return None
    return e, QUANT_BITS[variant]


def get_ci_test_models() -> List[CatalogEntry]:
    return [e for e in model_catalog if e.ci_test]
