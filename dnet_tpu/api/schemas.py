"""OpenAI-compatible request/response schemas + control-plane models.

Reference: src/dnet/api/models.py:51-236 (chat/completions with validators),
309-421 (topology prep / load / unload).  pydantic v2.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, Field, field_validator


class ChatMessage(BaseModel):
    role: Literal["system", "user", "assistant", "tool"]
    content: Union[str, List[Dict[str, Any]], None] = None

    def text(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if self.content is None:
            return ""
        parts = []
        for part in self.content:
            if isinstance(part, dict) and part.get("type") == "text":
                parts.append(part.get("text", ""))
        return "".join(parts)


class SamplingRequest(BaseModel):
    """Shared decode-request surface: sampling knobs + stop handling.

    Subclasses provide the prompt (`render_prompt`) and their
    default-token-limit; the decode driver (api/inference.py) works only
    against this base."""

    model: str
    temperature: float = Field(default=1.0, ge=0.0, le=2.0)
    top_p: float = Field(default=1.0, gt=0.0, le=1.0)
    top_k: int = Field(default=0, ge=0)
    min_p: float = Field(default=0.0, ge=0.0, le=1.0)
    repetition_penalty: float = Field(default=1.0, gt=0.0)
    # filters never shrink the candidate set below this (reference
    # DecodingConfig.min_tokens_to_keep, core/decoding/config.py:4-14)
    min_tokens_to_keep: int = Field(default=1, ge=1)
    max_tokens: Optional[int] = Field(default=None, ge=1)
    max_completion_tokens: Optional[int] = Field(default=None, ge=1)
    stream: bool = False
    stop: Optional[Union[str, List[str]]] = None
    seed: Optional[int] = None
    n: int = Field(default=1, ge=1, le=1)  # >1 unsupported (parity w/ reference)
    user: Optional[str] = None
    profile: bool = False  # dnet extension: include perf metrics in final chunk
    # dnet extension: end-to-end deadline for THIS request (seconds from
    # arrival), overriding DNET_REQUEST_DEADLINE_S.  Expired work is shed
    # at every stage — admission queue, decode driver, shard dequeue —
    # and surfaces as HTTP 504 (api/http.py).
    deadline_s: Optional[float] = Field(default=None, gt=0.0)
    # OpenAI logit_bias: token-id (stringified, per the OpenAI wire shape)
    # -> additive bias in [-100, 100].  APPLIED here (the reference's
    # DecodingConfig carries the field unused, src/dnet/api/models.py:70).
    logit_bias: Optional[Dict[str, float]] = None

    @field_validator("logit_bias")
    @classmethod
    def _check_logit_bias(cls, v):
        if not v:
            return v
        from dnet_tpu.core.sampler import MAX_LOGIT_BIAS

        if len(v) > MAX_LOGIT_BIAS:
            raise ValueError(
                f"logit_bias supports at most {MAX_LOGIT_BIAS} entries"
            )
        for tid, b in v.items():
            # ascii-decimal only: isdigit() admits unicode digits that
            # int() rejects, and token ids are never negative
            if not str(tid).isdecimal():
                raise ValueError(f"logit_bias key {tid!r} is not a token id")
            if not -100.0 <= b <= 100.0:
                raise ValueError("logit_bias values must be in [-100, 100]")
        return v

    def logit_bias_ids(self) -> Optional[Dict[int, float]]:
        """Int-keyed form for DecodingParams (OpenAI sends string keys)."""
        if not self.logit_bias:
            return None
        return {int(t): float(b) for t, b in self.logit_bias.items()}

    _default_max_tokens: int = 256

    @property
    def completion_tokens_limit(self) -> int:
        return self.max_completion_tokens or self.max_tokens or self._default_max_tokens

    def stop_sequences(self) -> List[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def render_prompt(self, tokenizer) -> str:
        raise NotImplementedError

    @property
    def logprobs_enabled(self) -> bool:
        """Whether per-token logprobs were requested (field semantics differ:
        chat uses a bool, legacy completions an Optional[int] where 0 still
        means 'chosen-token logprobs, no alternatives')."""
        return bool(getattr(self, "logprobs", False))


class ChatCompletionRequest(SamplingRequest):
    messages: List[ChatMessage]
    logprobs: bool = False
    top_logprobs: int = Field(default=0, ge=0, le=20)

    @field_validator("messages")
    @classmethod
    def _non_empty(cls, v):
        if not v:
            raise ValueError("messages must be non-empty")
        return v

    def render_prompt(self, tokenizer) -> str:
        return tokenizer.apply_chat_template(
            [m.model_dump() for m in self.messages], add_generation_prompt=True
        )


class CompletionRequest(SamplingRequest):
    """Legacy /v1/completions: a raw text prompt, no chat template
    (reference api/models.py carries the same schema family)."""

    prompt: Union[str, List[str]]
    # OpenAI completions: null disables; 0 = chosen-token logprobs only;
    # k > 0 adds the top-k alternatives
    logprobs: Optional[int] = Field(default=None, ge=0, le=20)
    echo: bool = False

    _default_max_tokens: int = 16

    @field_validator("prompt")
    @classmethod
    def _single_prompt(cls, v):
        if isinstance(v, list):
            if len(v) != 1:
                raise ValueError("batch prompts unsupported; send one prompt")
            if not isinstance(v[0], str):
                raise ValueError("prompt must be a string")
        return v

    def prompt_text(self) -> str:
        return self.prompt[0] if isinstance(self.prompt, list) else self.prompt

    def render_prompt(self, tokenizer) -> str:
        return self.prompt_text()

    @property
    def top_logprobs(self) -> int:
        return self.logprobs or 0

    @property
    def logprobs_enabled(self) -> bool:
        return self.logprobs is not None


class EmbeddingsRequest(BaseModel):
    model: str
    input: Union[str, List[str], List[int], List[List[int]]]
    encoding_format: Literal["float", "base64"] = "float"
    user: Optional[str] = None


class EmbeddingData(BaseModel):
    object: Literal["embedding"] = "embedding"
    index: int
    # list of floats, or a base64 little-endian f32 buffer
    # (encoding_format="base64", the OpenAI client's compact transfer mode)
    embedding: Union[List[float], str]


class EmbeddingsUsage(BaseModel):
    prompt_tokens: int
    total_tokens: int


class EmbeddingsResponse(BaseModel):
    object: Literal["list"] = "list"
    data: List[EmbeddingData]
    model: str
    usage: EmbeddingsUsage


class CompletionLogprobs(BaseModel):
    """OpenAI text_completion logprobs shape (NOT the chat shape)."""

    tokens: List[str] = Field(default_factory=list)
    token_logprobs: List[Optional[float]] = Field(default_factory=list)
    top_logprobs: List[Dict[str, float]] = Field(default_factory=list)
    text_offset: List[int] = Field(default_factory=list)


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    logprobs: Optional[CompletionLogprobs] = None
    finish_reason: Optional[str] = None


class CompletionResponse(BaseModel):
    id: str
    object: str = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[CompletionChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None
    metrics: Optional[RequestMetrics] = None


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class RequestMetrics(BaseModel):
    """dnet extension returned when profile=true.

    Reference: src/dnet/api/inference.py:216-233.  Since the obs subsystem,
    this is a VIEW over the request's flight-recorder timeline
    (dnet_tpu.obs.FlightRecorder): the driver records `ttft`, per-step
    `decode_step`, and a closing `request` span, and `from_timeline`
    derives every field from those — one measurement, two consumers
    (`/v1/debug/timeline/{rid}` dumps the same spans raw).
    """

    total_ms: float = 0.0
    ttfb_ms: float = 0.0
    token_gen_ms: float = 0.0
    tokens_generated: int = 0
    tps_overall: float = 0.0
    tps_decoding: float = 0.0
    # the per-request segment ledger (obs/critical_path.py decompose):
    # attached by the driver at request close so loadgen rows — and any
    # profile=true client — carry WHERE the E2E went, not just how much
    critical_path: Optional[dict] = None

    @classmethod
    def from_timeline(cls, timeline: Optional[dict]) -> "RequestMetrics":
        """Derive the profile fields from recorded spans.  Tolerates a
        missing timeline (recorder ring evicted the rid under extreme
        concurrency) by returning zeros rather than inventing numbers."""
        spans = (timeline or {}).get("spans", [])

        def last(name: str) -> Optional[dict]:
            return next(
                (s for s in reversed(spans) if s["name"] == name), None
            )

        req = last("request")
        if req is None:
            return cls()
        total_ms = float(req["dur_ms"])
        meta = req.get("meta") or {}
        tokens = int(
            meta.get(
                "tokens",
                sum(1 for s in spans if s["name"] == "decode_step"),
            )
        )
        ttft = last("ttft")
        if ttft is not None:
            ttfb_ms = float(ttft["dur_ms"])
        elif tokens:
            # ttft span lost (timeline evicted and auto-reopened
            # mid-request): attribute the whole duration to decoding
            # rather than clamping gen_ms to ~0 and reporting an
            # astronomical tps_decoding
            ttfb_ms = 0.0
        else:
            ttfb_ms = total_ms
        gen_ms = max(total_ms - ttfb_ms, 1e-9)
        return cls(
            total_ms=total_ms,
            ttfb_ms=ttfb_ms,
            token_gen_ms=gen_ms,
            tokens_generated=tokens,
            tps_overall=tokens / max(total_ms / 1000, 1e-9),
            tps_decoding=max(tokens - 1, 0) / (gen_ms / 1000),
        )


class TopLogprob(BaseModel):
    token: str
    logprob: float
    bytes: Optional[List[int]] = None


class LogprobEntry(BaseModel):
    token: str
    logprob: float
    bytes: Optional[List[int]] = None
    top_logprobs: List[TopLogprob] = Field(default_factory=list)


class ChoiceLogprobs(BaseModel):
    content: List[LogprobEntry] = Field(default_factory=list)


class ChatChoiceDelta(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None


class ChatStreamChoice(BaseModel):
    index: int = 0
    delta: ChatChoiceDelta = Field(default_factory=ChatChoiceDelta)
    logprobs: Optional[ChoiceLogprobs] = None
    finish_reason: Optional[str] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatStreamChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None
    metrics: Optional[RequestMetrics] = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    logprobs: Optional[ChoiceLogprobs] = None
    finish_reason: str = "stop"


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatChoice] = Field(default_factory=list)
    usage: Usage = Field(default_factory=Usage)
    metrics: Optional[RequestMetrics] = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "dnet-tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: List[ModelInfo] = Field(default_factory=list)


# ---- control plane --------------------------------------------------------


class LoadModelRequest(BaseModel):
    model: str
    kv_bits: int = 0
    max_seq_len: Optional[int] = None
    # ring mode: reuse weights on shards whose load body is unchanged —
    # only the epoch bumps and per-request state drops (delta reload,
    # dnet_tpu/membership/).  Recovery/rejoin always use the delta path;
    # this opts an operator-driven reload into it too.
    delta: bool = False


class LoadModelResponse(BaseModel):
    status: str = "ok"
    model: str = ""
    message: str = ""
    load_time_s: float = 0.0


class UnloadModelResponse(BaseModel):
    status: str = "ok"
    message: str = ""


class PrepareTopologyRequest(BaseModel):
    model: str
    kv_bits: int = 0
    seq_len: int = 4096


class ManualAssignment(BaseModel):
    instance: str
    layers: List[int]
    window_size: int = 0
    residency_size: int = 0
    # host-local mesh for this ring node (parallel/shard_mesh.py):
    # 0 = shard default, 1 = single chip, -1 tp = all local chips
    mesh_tp: int = 0
    mesh_sp: int = 0


class PrepareTopologyManualRequest(BaseModel):
    model: str
    assignments: List[ManualAssignment]
    kv_bits: int = 0


class HealthResponse(BaseModel):
    status: str = "ok"
    role: str = "api"
    model: Optional[str] = None


def new_request_id() -> str:
    return f"chatcmpl-{uuid.uuid4().hex[:24]}"
