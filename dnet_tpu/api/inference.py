"""The decode driver: chat request -> token loop -> SSE chunks.

Reference: src/dnet/api/inference.py:66-311 — template/encode, per-request
nonce, per-token send/await/detokenize loop, EOS + stop-sequence + length
stops, usage and profile metrics, and non-streaming aggregation.
"""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator, Optional

from dnet_tpu.api.schemas import (
    ChatChoice,
    ChatChoiceDelta,
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatCompletionResponse,
    ChatMessage,
    ChatStreamChoice,
    ChoiceLogprobs,
    LogprobEntry,
    RequestMetrics,
    TopLogprob,
    Usage,
    new_request_id,
)
from dnet_tpu.admission.controller import (
    AdmissionController,
    AdmissionRejected,
    Deadline,
    deadline_expired,
    request_deadline,
)
from dnet_tpu.api.strategies import ApiAdapterBase
from dnet_tpu.core.types import DecodingParams
from dnet_tpu.obs import critical_path, get_recorder, get_slo_tracker, metric
from dnet_tpu.obs.events import bind, log_event
from dnet_tpu.resilience.checkpoint import ResumableDecode
from dnet_tpu.resilience.policy import is_retryable
from dnet_tpu.utils.logger import get_logger
from dnet_tpu.utils.tokenizer import Detokenizer

log = get_logger()

_TTFT_MS = metric("dnet_ttft_ms")
_REQUESTS = metric("dnet_requests_total")
_REQUEST_ERRORS = metric("dnet_request_errors_total")
_TOKENS_TOTAL = metric("dnet_tokens_generated_total")
_CANCELS = metric("dnet_cancel_propagated_total")


class InferenceError(Exception):
    pass


class PromptTooLongError(InferenceError):
    """Maps to HTTP 400 (client error) rather than 500."""


class ServiceDegradedError(InferenceError):
    """Ring has DOWN shards: maps to HTTP 503 immediately (fast-fail
    instead of the reference's 300s token-future timeout)."""


class DeadlineExceededError(InferenceError):
    """The request's end-to-end deadline expired mid-flight: maps to
    HTTP 504 (api/http.py).  Raised by the driver's between-step check or
    classified from a shard's `deadline exceeded` error final."""


class BackpressureError(InferenceError):
    """A capacity limit refused the work (paged-KV pool exhausted, lane /
    batch-slot pools full): maps to HTTP 429 + Retry-After, never 500 —
    the client should back off and retry, nothing is broken."""


class EngineCapabilityError(InferenceError):
    """The loaded engine cannot serve the requested configuration —
    continuous batching over streamed weights, or a model without gated
    KV writes (raised by core/batch.py at LOAD time): maps to HTTP 422,
    an operator/config error, not the generic 500 it used to surface as
    when a NotImplementedError crossed /v1/load_model."""


# capacity-exhaustion signatures that cross the compute/wire boundary as
# error STRINGS (TokenResult.error); the single choke point turning them
# back into typed backpressure
_BACKPRESSURE_MARKERS = (
    "paged KV pool exhausted",   # kv/paged.py KVPoolExhausted
    "no free lanes",             # shard/lanes.py lane-pool overflow
    "no free batch slots",       # core/batch.py slot-pool overflow
)


def classify_result_error(error: str) -> InferenceError:
    """Map a step's error string to the typed exception the HTTP layer
    translates into a status code (429 backpressure / 504 deadline /
    500 otherwise)."""
    if "deadline exceeded" in error:
        return DeadlineExceededError(error)
    if any(marker in error for marker in _BACKPRESSURE_MARKERS):
        return BackpressureError(error)
    return InferenceError(error)


def _event_status(exc: BaseException) -> int:
    """HTTP status a failed request's `request_complete` wide event will
    carry — the same mapping api/http.py `_map_inference_errors` applies,
    duplicated here because the event must be journaled where the request
    FINISHES (the driver), not where the response serializes."""
    if isinstance(exc, AdmissionRejected):
        return 503 if exc.reason == "draining" else 429
    if isinstance(exc, BackpressureError):
        return 429
    if isinstance(exc, DeadlineExceededError):
        return 504
    if isinstance(exc, PromptTooLongError):
        return 400
    if isinstance(exc, EngineCapabilityError):
        return 422
    if isinstance(exc, ServiceDegradedError):
        return 503
    return 500


def _resolved_modes() -> dict:
    """The serving-mode knobs a postmortem reader wants next to a
    request's outcome: resolved wire codec, KV layout, TP degree, and
    whether the continuous-batching scheduler served it."""
    from dnet_tpu.config import get_settings

    s = get_settings()
    kv = "ragged" if s.kv.ragged else ("paged" if s.kv.paged else "dense")
    return {
        "codec": s.wire.codec,
        "kv": kv,
        "tp": int(s.tp.tp),
        "sched": bool(s.sched.sched),
    }


def completion_logprobs(entries: list, offset0: int = 0):
    """Chat-style LogprobEntry list -> the OpenAI text_completion logprobs
    shape ({tokens, token_logprobs, top_logprobs, text_offset})."""
    from dnet_tpu.api.schemas import CompletionLogprobs

    out = CompletionLogprobs()
    offset = offset0
    for e in entries:
        out.tokens.append(e.token)
        out.token_logprobs.append(e.logprob)
        out.top_logprobs.append({t.token: t.logprob for t in e.top_logprobs})
        out.text_offset.append(offset)
        offset += len(e.token)
    return out


def _holdback_len(text: str, stop_seqs: list[str]) -> int:
    """Length of the longest suffix of `text` that is a proper prefix of any
    stop sequence (must be held back — the next token may complete a stop)."""
    hold = 0
    for s in stop_seqs:
        for k in range(min(len(s) - 1, len(text)), 0, -1):
            if text.endswith(s[:k]):
                hold = max(hold, k)
                break
    return hold


class InferenceManager:
    def __init__(
        self,
        adapter: ApiAdapterBase,
        request_timeout_s: float = 300.0,
        max_concurrent: int = 8,
        admission: Optional[AdmissionController] = None,
    ) -> None:
        self.adapter = adapter
        self.tokenizer = None  # set by ModelManager on load
        self.model_id: Optional[str] = None
        self.request_timeout_s = request_timeout_s
        self._max_concurrent = max_concurrent
        if admission is None:
            from dnet_tpu.config import get_settings

            adm = get_settings().admission
            admission = AdmissionController(
                max_concurrent,
                queue_depth=adm.admit_queue_depth,
                queue_timeout_s=adm.admit_queue_timeout_s,
            )
        # the admission-aware front end replacing the old raw semaphore:
        # bounded queue, deadline-aware shedding, Retry-After estimates,
        # and drain mode all live here (dnet_tpu/admission/)
        self.admission = admission
        self.failure_monitor = None  # RingFailureMonitor in ring mode
        # detached cancel-cleanup tasks (client-disconnect fan-out): strong
        # refs so the loop's weak task set cannot GC a reclaim mid-flight
        self._cancel_cleanups: set = set()

    def set_concurrency_limit(self, n: Optional[int]) -> None:
        """Re-cap request admission (ring lanes: the shard lane pools hold
        exactly `lanes` KV rows, so admitting more mid-decode requests than
        lanes would hard-fail the overflow instead of queueing it).  None
        restores the configured default.  Requests already admitted finish
        under the old cap; new arrivals use the new one."""
        self.admission.set_capacity(n)

    @property
    def ready(self) -> bool:
        return self.tokenizer is not None and self.model_id is not None

    def _decoding(self, req: ChatCompletionRequest) -> DecodingParams:
        return DecodingParams(
            temperature=req.temperature,
            top_p=req.top_p,
            top_k=req.top_k,
            min_p=req.min_p,
            repetition_penalty=req.repetition_penalty,
            min_tokens_to_keep=req.min_tokens_to_keep,
            logprobs=req.logprobs_enabled,
            top_logprobs=req.top_logprobs,
            seed=req.seed,
            logit_bias=req.logit_bias_ids(),
            # EOS ids ride along so ring decode grants can halt shard-side
            stop_token_ids=tuple(self.tokenizer.eos_token_ids)
            if self.tokenizer is not None
            else (),
        )

    def _logprob_entry(self, result, text: str) -> LogprobEntry:
        top = [
            TopLogprob(
                token=self.tokenizer.decode([tid]),
                logprob=lp,
                bytes=list(self.tokenizer.decode([tid]).encode("utf-8")),
            )
            for tid, lp in (result.top_logprobs or [])
        ]
        return LogprobEntry(
            token=text,
            logprob=result.logprob or 0.0,
            bytes=list(text.encode("utf-8")),
            top_logprobs=top,
        )

    def _deadline_for(self, req) -> Optional[Deadline]:
        from dnet_tpu.config import get_settings

        return request_deadline(
            getattr(req, "deadline_s", None),
            get_settings().admission.request_deadline_s,
        )

    async def generate_stream(
        self, req: ChatCompletionRequest
    ) -> AsyncIterator[ChatCompletionChunk]:
        """Per-token chunks; final chunk carries finish_reason/usage/metrics.

        Admission happens on the consumer's FIRST `anext`: a shed request
        raises `AdmissionRejected` (429 + Retry-After upstream) before any
        chunk — the HTTP layer peeks the first chunk before committing to
        an SSE 200, so rejections keep real status codes."""
        if not self.ready:
            raise InferenceError("no model loaded")
        deadline = self._deadline_for(req)
        t_admit = time.perf_counter()
        try:
            async with self.admission.slot(deadline):
                # queued-at-the-gate time, measured here because the rid
                # does not exist yet: _run backdates it onto the timeline
                # as the admission_wait segment (obs/critical_path.py)
                admit_wait_ms = (time.perf_counter() - t_admit) * 1000.0
                async for chunk in self._run(
                    req, deadline, admit_wait_ms=admit_wait_ms
                ):
                    yield chunk
        except AdmissionRejected as rej:
            # shed at the gate, before a rid ever existed: still one
            # finished request, so it still owes its request_complete —
            # the only variant without a rid (nothing to correlate)
            log_event(
                "request_complete",
                status=_event_status(rej),
                finish_reason="shed",
                shed=True,
                shed_reason=rej.reason,
                tokens=0,
                total_ms=round((time.perf_counter() - t_admit) * 1000.0, 3),
            )
            raise

    async def _run(
        self,
        req: ChatCompletionRequest,
        deadline: Optional[Deadline] = None,
        admit_wait_ms: float = 0.0,
    ) -> AsyncIterator[ChatCompletionChunk]:
        rid = new_request_id()
        nonce = rid
        # request-identity binding (obs/events.py): every log record and
        # wide event in this request's dynamic extent carries the rid
        # automatically.  Entered manually so the function stays flat; the
        # finally below always exits it (bind guards the cross-Context
        # reset a loop-finalized generator would otherwise trip).
        ctx = bind(rid=rid, node="api")
        ctx.__enter__()
        t_start = time.perf_counter()
        t_first: Optional[float] = None
        generated = 0
        finish_reason = "length"
        recorder = get_recorder()
        slo = get_slo_tracker()  # rolling windows behind /health + dnet_slo_*
        completed = False  # guards the one-per-request request_complete
        cleanup_detached = False
        resume = None  # built once the wire session is prepared
        prompt_ids: list = []
        try:
            if (
                self.failure_monitor is not None
                and self.failure_monitor.degraded
            ):
                raise ServiceDegradedError(
                    f"ring degraded: shard(s) "
                    f"{self.failure_monitor.down_shards()} down"
                )
            tok = self.tokenizer
            prompt = req.render_prompt(tok)  # chat template or raw
            prompt_ids = tok.encode(prompt)
            decoding = self._decoding(req)
            stop_seqs = req.stop_sequences()
            eos = tok.eos_token_ids
            detok = Detokenizer(tok)
            max_new = req.completion_tokens_limit

            capacity = self.adapter.max_seq()
            if capacity is not None:
                if len(prompt_ids) >= capacity:
                    raise PromptTooLongError(
                        f"prompt is {len(prompt_ids)} tokens but the serving "
                        f"context is {capacity}"
                    )
                max_new = min(max_new, capacity - len(prompt_ids))

            recorder.begin(rid)  # flight-recorder timeline (rid == nonce)
            if admit_wait_ms > 0.0:
                # the wait happened BEFORE this timeline's origin: a
                # negative start offset keeps [0, e2e] the admitted window
                # while the segment ledger still carries the queued time
                # (and the sum still reconciles against the client-measured
                # E2E)
                recorder.span(
                    rid, "admission_wait", admit_wait_ms,
                    t_ms=-admit_wait_ms, force=True,
                )
            _REQUESTS.inc()
            pending = ""  # emitted-text buffer held back for stop-seq match
            held_entries: list = []  # logprob entries for held-back tokens
            emitted_ahead = 0  # emitted chars owned by the oldest held entry
            first_chunk = True  # first streamed delta carries role=assistant
            stopped_by_seq = False

            await self.adapter.reset_cache(nonce)
            if deadline is not None:
                # the deadline rides every activation frame header from
                # here: shards shed expired frames at dequeue (zero
                # compute), and the lane flusher sheds expired members
                # (api/ring.py)
                self.adapter.set_deadline(nonce, deadline.t_deadline)
            # resume controller: owns the wire nonce + step mapping so a
            # mid-decode shard failure can (behind DNET_RESILIENCE_RESUME=1)
            # checkpoint, wait out recovery, and replay prompt+generated on
            # the new topology without this generator — or the client —
            # noticing.  adapter is a GETTER: auto-recovery swaps it.
            resume = ResumableDecode(
                lambda: self.adapter,
                rid,
                prompt_ids,
                monitor=self.failure_monitor,
                timeout_s=self.request_timeout_s,
            )
            send_ids = list(prompt_ids)
            for step in range(max_new):
                if deadline is not None:
                    if deadline.expired:
                        # between-step shed: the client's deadline passed,
                        # so every further token is work nobody is waiting
                        # for
                        deadline_expired("api_step")
                        raise DeadlineExceededError(
                            f"request deadline expired after {generated} "
                            f"token(s)"
                        )
                    # re-bound the token await per step: a shard that
                    # hangs without dying must surface the 504 when the
                    # deadline passes, not after the frozen request
                    # timeout (remaining() shrinks every step)
                    resume.timeout_s = min(
                        self.request_timeout_s,
                        max(deadline.remaining(), 0.001),
                    )
                t_step = time.perf_counter()
                try:
                    # re-check per step: the monitor's one-shot fail_pending
                    # only covers futures pending at the DOWN transition; a
                    # request at a step boundary would otherwise hang the
                    # full timeout
                    if (
                        self.failure_monitor is not None
                        and self.failure_monitor.degraded
                    ):
                        raise ServiceDegradedError(
                            f"ring degraded: shard(s) "
                            f"{self.failure_monitor.down_shards()} down"
                        )
                    await resume.send(
                        send_ids, decoding, step, budget=max_new - step
                    )
                    result = await resume.await_token(step)
                    if result.error:
                        # typed: deadline / backpressure errors keep their
                        # HTTP semantics (504 / 429) across the wire
                        raise classify_result_error(result.error)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    # transparent resume: wait for auto-recovery, replay
                    # prompt + generated under a fresh nonce, and take the
                    # replay's sampled token as THIS step's result.
                    # Candidates: error tokens / degraded ring / await
                    # timeout, AND raw transport failures from the send
                    # path (a dead stream past its re-open budget raises
                    # ConnectionError or gRPC UNAVAILABLE here, not an
                    # error TokenResult).  Non-transient logic errors
                    # propagate.  None = resume disabled/exhausted —
                    # surface the failure as before (fast 503 /
                    # InferenceError).
                    if (
                        deadline is not None
                        and deadline.expired
                        and isinstance(exc, asyncio.TimeoutError)
                    ):
                        # the deadline-bounded await lapsed: this is the
                        # deadline expiring mid-step, not a generic hang
                        deadline_expired("api_step")
                        raise DeadlineExceededError(
                            f"request deadline expired awaiting step "
                            f"{step}"
                        ) from exc
                    if isinstance(
                        exc, (DeadlineExceededError, BackpressureError)
                    ):
                        # shed work is not failed work: replaying a request
                        # nobody waits for (or that capacity just refused)
                        # would recreate the very overload being shed
                        raise
                    if not (
                        isinstance(
                            exc, (InferenceError, asyncio.TimeoutError)
                        )
                        or is_retryable(exc)
                    ):
                        raise
                    result = await resume.try_resume(
                        exc, decoding, step, budget=max_new - step
                    )
                    if result is None:
                        raise
                # one span per emitted token: send -> token resolved (grant /
                # chunk-buffered steps resolve in ~0ms, visibly so)
                step_ms = (time.perf_counter() - t_step) * 1000
                recorder.span(rid, "decode_step", step_ms, step=step)
                if step > 0:
                    # step 0 is the prefill pass — TTFT owns it; folding
                    # it into the decode window would read a long prompt
                    # as a decode-p95 SLO burn
                    slo.record_decode(step_ms)
                if t_first is None:
                    t_first = time.perf_counter()
                    ttft_ms = (t_first - t_start) * 1000
                    _TTFT_MS.observe(ttft_ms)
                    slo.record_ttft(ttft_ms)
                    # force: summary spans must survive the per-request
                    # span cap on generations long enough to out-span it
                    recorder.span(rid, "ttft", ttft_ms, t_ms=0.0, force=True)
                generated += 1
                _TOKENS_TOTAL.inc()

                if result.token_id in eos:
                    finish_reason = "stop"
                    break

                delta = detok.add(result.token_id)
                send_ids = [result.token_id]
                # checkpoint the accepted token: a later resume replays
                # prompt + generated so far (EOS breaks above — it never
                # extends context and never needs replaying)
                resume.record(result.token_id)
                # one logprob entry per generated token, carrying the
                # token's OWN text — holdback buffering must not smear one
                # token's logprob across text accumulated from several
                if req.logprobs_enabled:
                    held_entries.append(self._logprob_entry(result, delta))

                # Stop sequences: never emit text at or beyond a match, and
                # hold back any suffix that could still become one.
                stopped = False
                if stop_seqs:
                    pending += delta
                    delta = ""
                    for s in stop_seqs:
                        idx = pending.find(s)
                        if idx != -1:
                            pending = pending[:idx]
                            stopped = True
                            break
                    if stopped:
                        delta, pending = pending, ""
                    else:
                        hold = _holdback_len(pending, stop_seqs)
                        emit_upto = len(pending) - hold
                        delta, pending = pending[:emit_upto], pending[emit_upto:]

                if delta or stopped:
                    logprobs = None
                    if req.logprobs_enabled and held_entries:
                        # flush only entries whose token text is FULLY
                        # emitted; an entry whose text straddles the
                        # holdback boundary stays held with its text (a
                        # later stop match must be able to discard it —
                        # flushing early would leave a logprob entry for
                        # text that never reaches the client)
                        budget = emitted_ahead + len(delta)
                        kept = []
                        while held_entries and len(held_entries[0].token) <= budget:
                            budget -= len(held_entries[0].token)
                            kept.append(held_entries.pop(0))
                        if stopped:
                            # entries for the matched stop text are
                            # discarded with it
                            held_entries = []
                            emitted_ahead = 0
                        else:
                            emitted_ahead = budget
                        if kept:
                            logprobs = ChoiceLogprobs(content=kept)
                    yield ChatCompletionChunk(
                        id=rid,
                        model=req.model,
                        choices=[
                            ChatStreamChoice(
                                # the FIRST delta carries the role, as the
                                # OpenAI stream protocol (and client) expect
                                delta=ChatChoiceDelta(
                                    role=("assistant" if first_chunk else None),
                                    content=delta,
                                ),
                                logprobs=logprobs,
                            )
                        ],
                    )
                    first_chunk = False
                if stopped:
                    finish_reason = "stop"
                    stopped_by_seq = True
                    break

            # On EOS/length the held-back text is real content — flush it
            # (with any logprob entries still held back with it).  Only a
            # stop-sequence match discards its own matched text.
            tail = pending + detok.flush() if not stopped_by_seq else ""
            if tail or (held_entries and not stopped_by_seq):
                logprobs = (
                    ChoiceLogprobs(content=held_entries)
                    if req.logprobs_enabled and held_entries and not stopped_by_seq
                    else None
                )
                yield ChatCompletionChunk(
                    id=rid,
                    model=req.model,
                    choices=[
                        ChatStreamChoice(
                            delta=ChatChoiceDelta(
                                role=("assistant" if first_chunk else None),
                                content=tail,
                            ),
                            logprobs=logprobs,
                        )
                    ],
                )
                first_chunk = False

            t_end = time.perf_counter()
            usage = Usage(
                prompt_tokens=len(prompt_ids),
                completion_tokens=generated,
                total_tokens=len(prompt_ids) + generated,
            )
            # the request span closes the timeline; RequestMetrics is a VIEW
            # over the recorded spans (ttft + per-step + this), not a second
            # hand-maintained set of stopwatch fields
            recorder.span(
                rid, "request", (t_end - t_start) * 1000, t_ms=0.0,
                tokens=generated, prompt_tokens=len(prompt_ids),
                finish_reason=finish_reason, force=True,
            )
            # the segment ledger feeds dnet_request_segment_ms for EVERY
            # request (aggregate attribution is a serving concern, not a
            # profile=true opt-in); the structured dict additionally rides
            # the final chunk when the client asked to profile
            ledger = critical_path.decompose(recorder.timeline(rid))
            critical_path.observe(ledger)
            # the canonical wide event: exactly ONE per finished request,
            # embedding the same ledger so status/tokens/total_ms reconcile
            # with dnet_request_segment_ms by construction
            log_event(
                "request_complete",
                status=200,
                finish_reason=finish_reason,
                shed=False,
                tokens=generated,
                prompt_tokens=len(prompt_ids),
                total_ms=round((t_end - t_start) * 1000.0, 3),
                modes=_resolved_modes(),
                critical_path=ledger,
            )
            completed = True
            metrics = None
            if req.profile:
                metrics = RequestMetrics.from_timeline(recorder.timeline(rid))
                metrics.critical_path = ledger
            yield ChatCompletionChunk(
                id=rid,
                model=req.model,
                choices=[
                    ChatStreamChoice(
                        # a stream with zero content deltas (immediate EOS /
                        # whole output held back by a stop-seq) still owes
                        # the client the initial role chunk
                        delta=ChatChoiceDelta(
                            role=("assistant" if first_chunk else None)
                        ),
                        finish_reason=finish_reason,
                    )
                ],
                usage=usage,
                metrics=metrics,
            )
            slo.record_request(ok=True)
        except (GeneratorExit, asyncio.CancelledError):
            # the client went away (an SSE disconnect closes this
            # generator; a task cancel lands here too): fan the cancel out
            # through the ring NOW as a DETACHED task — the dying request
            # task must not be able to interrupt the reset_cache fan-out
            # that reclaims shard lanes and paged-KV blocks.  The
            # admission slot itself frees in generate_stream's
            # `async with` as this exception keeps propagating.
            _CANCELS.inc()
            if not completed:
                # still a finished request from the server's side: 499 is
                # the client-closed-request convention
                log_event(
                    "request_complete",
                    status=499,
                    finish_reason="cancelled",
                    shed=False,
                    tokens=generated,
                    prompt_tokens=len(prompt_ids),
                    total_ms=round(
                        (time.perf_counter() - t_start) * 1000.0, 3
                    ),
                    modes=_resolved_modes(),
                )
                completed = True
            cleanup_detached = True
            if resume is not None:
                task = asyncio.ensure_future(resume.cleanup())
                self._cancel_cleanups.add(task)
                task.add_done_callback(self._cancel_cleanups.discard)
            raise
        except Exception as exc:
            # client disconnects / task cancels (BaseException) are not
            # server errors; InferenceError and friends are.  Shed work is
            # not FAILED work either (the PR 5 status-code contract): a 429
            # capacity refusal or 504 expired deadline must not burn the
            # availability SLO or the error counter — otherwise every
            # overload the admission layer survives correctly would read
            # as an outage, and the load harness's availability (which
            # also excludes shed) could never cross-validate against the
            # live gauge.  Shed volume stays visible through
            # dnet_admit_rejected_total / dnet_deadline_exceeded_total.
            shed = isinstance(exc, (BackpressureError, DeadlineExceededError))
            if not shed:
                _REQUEST_ERRORS.inc()
                slo.record_request(ok=False)
            if not completed:
                log_event(
                    "request_complete",
                    status=_event_status(exc),
                    finish_reason="shed" if shed else "error",
                    shed=shed,
                    shed_reason=(
                        "deadline"
                        if isinstance(exc, DeadlineExceededError)
                        else "backpressure" if shed else ""
                    ),
                    error=str(exc)[:200],
                    tokens=generated,
                    prompt_tokens=len(prompt_ids),
                    total_ms=round(
                        (time.perf_counter() - t_start) * 1000.0, 3
                    ),
                    modes=_resolved_modes(),
                )
                completed = True
            raise
        finally:
            # guarded cleanup: reset_cache can itself raise when the ring
            # just died, which would mask the original error and crash the
            # SSE generator — the controller logs + swallows transport
            # errors on this path only
            try:
                if resume is not None and not cleanup_detached:
                    await resume.cleanup()
            finally:
                ctx.__exit__(None, None, None)

    async def embeddings(self, req) -> "EmbeddingsResponse":
        """Serve /v1/embeddings: mean-pooled final-hidden-state vectors
        (beyond the reference, which schemas the route but never serves
        it).  Accepts the full OpenAI input envelope — a string, a list of
        strings, a token list, or a batch of token lists — and the base64
        encoding_format.  Embeddings compete for the same compute as
        decode, so they pass the same admission controller — an
        embeddings burst is bounded, shed with 429s, and drained like
        everything else."""
        async with self.admission.slot(self._deadline_for(req)):
            return await self._embeddings(req)

    async def _embeddings(self, req) -> "EmbeddingsResponse":
        from dnet_tpu.api.schemas import (
            EmbeddingData,
            EmbeddingsResponse,
            EmbeddingsUsage,
        )

        raw = req.input
        if isinstance(raw, str):
            batches = [self.tokenizer.encode(raw)]
        elif raw and isinstance(raw[0], str):
            batches = [self.tokenizer.encode(s) for s in raw]
        elif raw and isinstance(raw[0], list):
            batches = [list(ids) for ids in raw]
        else:
            batches = [list(raw)]
        if any(not b for b in batches):
            raise ValueError("embeddings input contains an empty entry")
        vecs = await self.adapter.embed(batches)
        if req.encoding_format == "base64":
            import base64

            import numpy as np

            vecs = [
                base64.b64encode(
                    np.asarray(v, dtype=np.float32).tobytes()
                ).decode("ascii")
                for v in vecs
            ]
        data = [EmbeddingData(index=i, embedding=v) for i, v in enumerate(vecs)]
        n_tok = sum(len(b) for b in batches)
        return EmbeddingsResponse(
            data=data,
            model=self.model_id or req.model,
            usage=EmbeddingsUsage(prompt_tokens=n_tok, total_tokens=n_tok),
        )

    async def generate_completion(self, req) -> "CompletionResponse":
        """Legacy /v1/completions (non-streaming): aggregate the same decode
        stream into a text_completion object."""
        from dnet_tpu.api.schemas import CompletionChoice, CompletionResponse

        rid, text, logprob_entries, finish_reason, usage, metrics = (
            await self._collect(req)
        )
        offset0 = 0
        if req.echo:
            text = req.prompt_text() + text
            offset0 = len(req.prompt_text())
        return CompletionResponse(
            id=rid.replace("chatcmpl", "cmpl"),
            model=req.model,
            choices=[
                CompletionChoice(
                    text=text,
                    logprobs=completion_logprobs(logprob_entries, offset0)
                    if req.logprobs_enabled
                    else None,
                    finish_reason=finish_reason,
                )
            ],
            usage=usage,
            metrics=metrics,
        )

    async def _collect(self, req):
        """Drain the decode stream into (rid, text, logprob entries,
        finish_reason, usage, metrics) — shared by both non-streaming
        endpoints."""
        parts: list[str] = []
        logprob_entries: list[LogprobEntry] = []
        usage = Usage()
        metrics = None
        finish_reason = "stop"
        rid = new_request_id()
        async for chunk in self.generate_stream(req):
            rid = chunk.id
            for choice in chunk.choices:
                if choice.delta.content:
                    parts.append(choice.delta.content)
                if choice.logprobs:
                    logprob_entries.extend(choice.logprobs.content)
                if choice.finish_reason:
                    finish_reason = choice.finish_reason
            if chunk.usage:
                usage = chunk.usage
            if chunk.metrics:
                metrics = chunk.metrics
        return rid, "".join(parts), logprob_entries, finish_reason, usage, metrics

    async def generate(self, req: ChatCompletionRequest) -> ChatCompletionResponse:
        """Non-streaming: aggregate the stream (reference inference.py:255-311)."""
        rid, text, logprob_entries, finish_reason, usage, metrics = (
            await self._collect(req)
        )
        return ChatCompletionResponse(
            id=rid,
            model=req.model,
            choices=[
                ChatChoice(
                    message=ChatMessage(role="assistant", content=text),
                    logprobs=ChoiceLogprobs(content=logprob_entries) if req.logprobs_enabled else None,
                    finish_reason=finish_reason,
                )
            ],
            usage=usage,
            metrics=metrics,
        )
