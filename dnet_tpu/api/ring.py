"""Ring strategy on the API node: token injection + token-callback receipt.

Reference: RingApiAdapter (src/dnet/api/strategies/ring.py:125-209) and the
ShardApi gRPC servicer (src/dnet/api/grpc_servicer/servicer.py:19-37).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from dataclasses import asdict
from typing import Callable, Dict, List, Optional

import numpy as np

from dnet_tpu.admission.controller import deadline_expired
from dnet_tpu.api.strategies import ApiAdapterBase, _TokenFutures
from dnet_tpu.core.types import DecodingParams, TokenResult
from dnet_tpu.membership import epoch as epoch_fence
from dnet_tpu.obs import get_recorder, metric
from dnet_tpu.transport.protocol import ActivationFrame, Empty, TokenPayload
from dnet_tpu.transport.stream_manager import StreamManager
from dnet_tpu.utils.logger import get_logger
from dnet_tpu.utils.serialization import tensor_to_bytes

log = get_logger()

_HOP_RTT_MS = metric("dnet_ring_hop_rtt_ms")
_LANE_DEPTH = metric("dnet_lane_flush_depth")
_LANE_WAIT_MS = metric("dnet_lane_queue_wait_ms")
_PREFIX_REFILL = metric("dnet_prefix_refill_total")


class RingApiAdapter(ApiAdapterBase):
    """Streams token frames to the head shard; resolves tokens arriving at
    the API gRPC servicer."""

    def __init__(
        self,
        head_addr: str,
        callback_url: str,
        shard_grpc_addrs: Optional[List[str]] = None,
        ring_client_factory: Optional[Callable[[str], object]] = None,
        max_seq_len: Optional[int] = None,
        stream_idle_s: float = 300.0,
        auto_steps: int = 0,
        lanes: int = 1,
        prefix_cache: int = 0,
        epoch: int = 0,
    ) -> None:
        from dnet_tpu.transport.grpc_transport import RingClient

        self.head_addr = head_addr
        self.callback_url = callback_url
        # topology epoch this adapter serves (dnet_tpu/membership/):
        # stamped into every frame header and reset RPC; token callbacks
        # minted under any OTHER nonzero epoch are zombies and are dropped
        # (counted) in resolve_token.  0 = unfenced (single-process tests).
        self._epoch = int(epoch)
        self.shard_addrs = shard_grpc_addrs or [head_addr]
        self._make_client = ring_client_factory or (lambda addr: RingClient(addr))
        self._head_client = None
        self._streams: Optional[StreamManager] = None
        self._futures = _TokenFutures()
        self._max_seq = max_seq_len
        self._stream_idle_s = stream_idle_s
        self._sweeper: Optional[asyncio.Task] = None
        self._pos_state: Dict[str, int] = {}  # nonce -> prompt length (pos derives from step)
        # nonce -> absolute wall-clock deadline (epoch s): stamped into
        # every frame header so shards drop expired work at dequeue
        self._deadlines: Dict[str, float] = {}
        self._shard_clients: Dict[str, object] = {}
        # decode grants (ring self-continuation): a frame may authorize the
        # tail shard to feed up to `auto_steps` sampled tokens straight back
        # into the ring, so those steps cost no API round trip.  Tokens for
        # granted steps can arrive BEFORE the driver awaits them — they
        # stash in _early until send_tokens registers the future.
        # batched lanes (r5): with lanes > 1, concurrent requests' decode
        # steps COALESCE into multi-lane frames — the ring serves N nonces
        # per pass instead of N passes.  Grants are per-nonce self-pacing
        # and would pull members out of the shared cadence: lanes win.
        self._lanes = max(int(lanes), 1)
        self._auto_steps = 0 if self._lanes > 1 else max(int(auto_steps), 0)
        self._granted: Dict[str, int] = {}  # nonce -> highest granted step
        self._early: Dict[tuple, TokenResult] = {}
        self._pending: List[dict] = []  # lane entries awaiting a flush
        self._flush_task: Optional[asyncio.Task] = None
        self._batch_seq = 0
        # observed send->resolve latency EMA (seconds): sizes the lane
        # convergence window to ~1.5 ring passes
        self._step_ema = 0.0
        self._sent_at: Dict[tuple, float] = {}
        # nonces mid-generation (first send -> reset): the flusher holds a
        # batch open only while MORE active streams could still join it
        self._active: Dict[str, bool] = {}
        # ring prefix caching (r5): the API alone sees token ids, so IT
        # matches prefixes and keys every shard-side snapshot through the
        # prompt frames.  The index (shared PrefixIndex matcher, values =
        # snapshot keys) mirrors the shards' SnapshotStore LRUs (same
        # capacity, same put/get sequence); a shard-side miss (e.g. a
        # restarted shard) error-fails that request with `prefix-miss:<key>`
        # and invalidates the entry here, so the next request re-stores.
        from dnet_tpu.core.prefix_cache import PrefixIndex

        self._prefix_cap = max(int(prefix_cache), 0)
        self._prefix_index = PrefixIndex(
            max(self._prefix_cap, 1), self.PREFIX_MIN_TOKENS
        )
        # transparent prefix refill: while a suffix-only prefill (prefix
        # hit) is in flight, the FULL prompt is stashed here so a shard-side
        # `prefix-miss:` failure re-sends a full prefill instead of
        # surfacing an InferenceError (popped on step-0 resolution either
        # way — one retry per request, a second miss fails loudly)
        self._refill_state: Dict[str, dict] = {}
        # strong refs to in-flight refill tasks: the loop only keeps a
        # weak one, so a bare ensure_future could be GC'd mid-refill and
        # its exceptions vanish (DL003)
        self._refill_tasks: set = set()

    async def start(self) -> None:
        self._head_client = self._make_client(self.head_addr)
        self._streams = StreamManager(
            self._head_client.open_stream,
            idle_timeout_s=self._stream_idle_s,
            on_nack=self._on_stream_nack,
        )
        # persistent control channels to every shard (reset fan-out per
        # request must not pay N channel handshakes)
        self._shard_clients = {
            addr: self._make_client(addr) for addr in self.shard_addrs
        }
        self._sweeper = asyncio.ensure_future(self._idle_sweep())

    async def shutdown(self) -> None:
        if self._sweeper:
            self._sweeper.cancel()
            self._sweeper = None
        if self._streams:
            await self._streams.shutdown()
            self._streams = None
        # per-shard channels close concurrently (independent teardown);
        # a failed close still surfaces, after every close was attempted
        outcomes = await asyncio.gather(
            *(c.close() for c in self._shard_clients.values()),
            return_exceptions=True,
        )
        self._shard_clients = {}
        for exc in outcomes:
            if isinstance(exc, Exception):
                raise exc
        if self._head_client is not None:
            await self._head_client.close()
            self._head_client = None

    def max_seq(self) -> Optional[int]:
        return self._max_seq

    def _on_stream_nack(self, ack) -> None:
        """A shard REFUSED a frame outright (epoch fence): fail the
        awaiting step now instead of letting the driver burn its full
        token timeout — the refusal is definitive, the token can never
        come.  This is how an adapter that turned zombie mid-request
        (its topology re-solved underneath) fails fast so the resume
        controller can replay on the NEW adapter.  Batch carrier frames
        have no future of their own and other NACK kinds (relay hiccups)
        keep their existing retry semantics."""
        if ack.nonce == self.LANES_NONCE:
            return
        if not str(ack.message).startswith("stale epoch"):
            return
        self.resolve_token(
            TokenResult(
                nonce=ack.nonce, token_id=-1, step=ack.seq,
                error=f"frame rejected: {ack.message}",
            )
        )

    def set_deadline(self, nonce: str, deadline_ts: float) -> None:
        if deadline_ts > 0:
            self._deadlines[nonce] = float(deadline_ts)

    async def reset_cache(self, nonce: str) -> None:
        """Reset per-nonce KV on every shard (gRPC fan-out, reference
        inference.py:118)."""
        self._futures.cancel_nonce(nonce)
        self._pos_state.pop(nonce, None)
        self._deadlines.pop(nonce, None)
        self._granted.pop(nonce, None)
        self._active.pop(nonce, None)
        self._refill_state.pop(nonce, None)
        if self._pending:
            self._pending = [e for e in self._pending if e["nonce"] != nonce]
        for key in [k for k in self._sent_at if k[0] == nonce]:
            self._sent_at.pop(key, None)
        for key in [k for k in self._early if k[0] == nonce]:
            self._early.pop(key, None)
        if self._streams is not None:
            await self._streams.end_stream(nonce)

        async def _reset(addr: str, client) -> None:
            try:
                await client.reset_cache(nonce, epoch=self._epoch)
            except Exception as exc:
                log.warning("reset_cache on %s failed: %s", addr, exc)

        await asyncio.gather(
            *(_reset(a, c) for a, c in self._shard_clients.items())
        )

    async def send_tokens(
        self,
        nonce: str,
        token_ids: List[int],
        decoding: DecodingParams,
        step: int,
        budget: Optional[int] = None,
    ) -> None:
        if self._streams is None:
            raise RuntimeError("adapter not started")
        self._futures.expect(nonce, step)
        if step > 0 and step <= self._granted.get(nonce, -1):
            # this step's token is already being produced by the ring
            # itself (decode grant) — no frame; resolve now if it beat us
            early = self._early.pop((nonce, step), None)
            if early is not None:
                self._futures.resolve(early)
            return
        if self._lanes > 1 and step > 0:
            # mid-DECODE streams only: prefilling requests must not count
            # toward the coalesce target (a long prefill would stall every
            # flush for the full convergence window)
            self._active[nonce] = True
            # coalesce: enqueue this decode step and let the flusher build
            # a multi-lane frame from every same-tick sender (concurrent
            # drivers resolve together, so their next steps arrive together)
            self._pending.append(
                {
                    "nonce": nonce,
                    "seq": step,
                    "pos": self._pos_for(nonce, step, len(token_ids)),
                    "decoding": asdict(decoding),
                    "token": int(token_ids[0]),
                    "t_enq": time.monotonic(),  # lane queue-wait origin
                }
            )
            if self._flush_task is None or self._flush_task.done():
                self._flush_task = asyncio.ensure_future(self._flush_lanes())
            return
        pos = self._pos_for(nonce, step, len(token_ids))
        send_ids = token_ids
        prefix_hit = prefix_store = ""
        if step == 0 and self._prefix_cap > 0:
            ids = tuple(token_ids)
            hit = self._prefix_lookup(ids)
            if hit is not None:
                pos, prefix_hit = hit
                get_recorder().span(nonce, "prefix_cache_hit", 0.0, tokens=pos)
                send_ids = token_ids[pos:]  # prefill only the new suffix
                # stash the full prompt: a shard-side prefix-miss re-sends
                # it as a full prefill instead of failing the request
                self._refill_state[nonce] = {
                    "token_ids": list(token_ids),
                    "decoding": decoding,
                    "budget": budget,
                }
            if len(ids) >= self.PREFIX_MIN_TOKENS:
                prefix_store = self._prefix_put(ids)
        await self._send_token_frame(
            nonce, send_ids, pos, decoding, step, budget,
            prefix_hit=prefix_hit, prefix_store=prefix_store,
        )

    async def _send_token_frame(
        self,
        nonce: str,
        send_ids: List[int],
        pos: int,
        decoding: DecodingParams,
        step: int,
        budget: Optional[int],
        prefix_hit: str = "",
        prefix_store: str = "",
    ) -> None:
        """Build and send one token frame, sizing (and registering) the
        decode grant from the remaining budget — the single frame path for
        normal sends AND the prefix-refill retry, so the two cannot drift."""
        auto = 0
        if self._auto_steps > 0 and budget is not None and budget > 1:
            auto = min(self._auto_steps, budget - 1)
        payload, _dtype, shape = tensor_to_bytes(
            np.asarray([send_ids], dtype=np.int32)
        )
        frame = ActivationFrame(
            nonce=nonce,
            seq=step,
            layer_id=-1,
            pos=pos,
            dtype="tokens",
            shape=shape,
            payload=payload,
            callback_url=self.callback_url,
            decoding=asdict(decoding),
            t_sent=time.time(),
            t_sent_mono=time.perf_counter(),
            auto_steps=auto,
            prefix_hit=prefix_hit,
            prefix_store=prefix_store,
            deadline=self._deadlines.get(nonce, 0.0),
            epoch=self._epoch,
        )
        if auto:
            self._granted[nonce] = step + auto
        await self._streams.send(nonce, frame)

    LANES_NONCE = "__lanes__"  # carrier stream for coalesced decode frames
    # convergence window bounds: how long a partially-filled batch may hold
    # open for more mid-decode streams to join.  This is a CONVERGENCE
    # cost, not a per-token cost — members of one batch resolve together
    # and re-send together, so once streams merge they stay merged and the
    # wait collapses to ~0.  The window ADAPTS to the observed step time
    # (a multi-host ring pass can exceed any fixed constant; streams offset
    # by up to ~1.5 steps must still merge on the first wait).  A solo
    # stream (one active nonce) never waits at all.
    LANE_CONVERGE_MIN_S = 0.05
    LANE_CONVERGE_MAX_S = 1.0

    # window = multiplier x the observed ring-pass EMA.  The EMA is stamped
    # at the actual frame FLUSH (not the enqueue), so it measures the pure
    # ring pass; the old enqueue-stamped EMA silently folded each batch's
    # own convergence wait back into the window (a positive feedback the
    # multiplier then under-stated).  With the honest, smaller EMA the
    # multiplier carries the full jitter allowance itself: ~2.5 passes
    # absorbs driver-coroutine scheduling offset without the feedback loop.
    LANE_CONVERGE_EMA_MULT = 2.5

    def _converge_window(self) -> float:
        ema = self._step_ema
        if ema <= 0:
            return self.LANE_CONVERGE_MIN_S
        return min(max(self.LANE_CONVERGE_EMA_MULT * ema,
                       self.LANE_CONVERGE_MIN_S),
                   self.LANE_CONVERGE_MAX_S)

    async def _flush_lanes(self) -> None:
        """Drain pending lane entries into multi-lane frames.  A batch
        holds open (bounded by the adaptive convergence window) while more
        mid-decode streams could still join; per-nonce ordering is the
        driver's (it never sends step k+1 before step k resolved)."""
        await asyncio.sleep(0)
        loop = asyncio.get_running_loop()
        while self._pending:
            target = min(self._lanes, len(self._active))
            if len(self._pending) < target:
                deadline = loop.time() + self._converge_window()
                while len(self._pending) < target and loop.time() < deadline:
                    await asyncio.sleep(0.0005)
            batch = self._pending[: self._lanes]
            self._pending = self._pending[len(batch):]
            # shed expired members HERE rather than stamping the batch
            # frame: one late member must not expire the whole frame at a
            # shard dequeue and kill its live co-members
            live = []
            for e in batch:
                dl = self._deadlines.get(e["nonce"], 0.0)
                if dl and time.time() >= dl:
                    deadline_expired("lane_flush")
                    self.resolve_token(
                        TokenResult(
                            nonce=e["nonce"], token_id=-1, step=e["seq"],
                            error="deadline exceeded at lane flush",
                        )
                    )
                    continue
                live.append(e)
            batch = live
            if not batch:
                continue
            _LANE_DEPTH.observe(len(batch))
            now = time.monotonic()
            for e in batch:
                wait_ms = (now - e["t_enq"]) * 1000
                _LANE_WAIT_MS.observe(wait_ms)
                get_recorder().span(
                    e["nonce"], "lane_queue_wait", wait_ms, step=e["seq"]
                )
                # send-origin stamped at the actual flush, NOT the enqueue:
                # the hop RTT (and the _step_ema convergence window it
                # feeds) must measure the ring pass alone — folding the
                # batch's own convergence wait in would inflate the EMA,
                # which widens the window, which inflates the EMA further
                self._sent_at[(e["nonce"], e["seq"])] = now
            tokens = np.asarray([[e["token"]] for e in batch], dtype=np.int32)
            payload, _dtype, shape = tensor_to_bytes(tokens)
            # dnetlint: disable=DL008 lane batch frame: many requests share it, so a single deadline would fate-share lanes; per-request deadlines are enforced at API admission and per-lane resolve
            frame = ActivationFrame(
                nonce=self.LANES_NONCE,
                seq=self._batch_seq,
                layer_id=-1,
                pos=0,
                dtype="tokens",
                shape=shape,
                payload=payload,
                callback_url=self.callback_url,
                decoding={},
                t_sent=time.time(),
                t_sent_mono=time.perf_counter(),
                lanes=[
                    {k: e[k] for k in ("nonce", "seq", "pos", "decoding")}
                    for e in batch
                ],
                epoch=self._epoch,
            )
            self._batch_seq += 1
            log.info(
                "[PROFILE] lane flush: %d member(s), %d active, %d still pending",
                len(batch), len(self._active), len(self._pending),
            )
            try:
                await self._streams.send(self.LANES_NONCE, frame)
            except Exception as exc:
                # fail every member alone and fast; their drivers surface
                # the error instead of blocking the full request timeout
                # (drop the send stamps first: a failed send is not a hop,
                # and a ~0ms "RTT" would poison the _step_ema)
                for e in batch:
                    self._sent_at.pop((e["nonce"], e["seq"]), None)
                    self.resolve_token(
                        TokenResult(
                            nonce=e["nonce"], token_id=-1, step=e["seq"],
                            error=f"batch frame send failed: {exc}",
                        )
                    )

    PREFIX_MIN_TOKENS = 16  # tiny prompts aren't worth a snapshot

    def _prefix_lookup(self, ids: tuple):
        """Longest indexed strict-proper-prefix of `ids` (matching rules —
        and the hit/miss counters — owned by core.prefix_cache.PrefixIndex).
        (n_tokens, key) or None."""
        return self._prefix_index.lookup(ids)

    def _prefix_put(self, ids: tuple) -> str:
        """Index the full prompt and return its store key (shards snapshot
        under it as the prompt frame passes)."""
        key = self._prefix_index.get_exact(ids)
        if key is None:
            key = hashlib.sha1(
                np.asarray(ids, dtype=np.int64).tobytes()
            ).hexdigest()[:16]
            self._prefix_index.put(ids, key)  # PrefixIndex counts the store
        return key

    def _pos_for(self, nonce: str, step: int, n_tokens: int) -> int:
        """Step 0 injects the whole prompt at pos 0; every later step
        appends exactly ONE token, so pos is DERIVED (prompt_len + step - 1)
        rather than counted.  Grants need no pre-advance bookkeeping, and a
        grant that halts early (EOS, stop sequence, error) cannot leave a
        skewed counter behind for later frames — each frame's pos is
        recomputed from its step."""
        if step == 0:
            self._pos_state[nonce] = n_tokens  # prompt length
            return 0
        assert n_tokens == 1, "post-prompt frames carry exactly one token"
        return self._pos_state.get(nonce, 0) + step - 1

    async def await_token(self, nonce: str, step: int, timeout: float) -> TokenResult:
        return await self._futures.wait(nonce, step, timeout)

    def resolve_token(self, result: TokenResult) -> None:
        # Zombie fence (dnet_tpu/membership/): a token minted under a dead
        # topology epoch — a fenced-out shard finishing in-flight compute,
        # a partitioned "dead" shard coming back — must never resolve a
        # live future or reach an SSE stream.  Counted, then dropped.
        if epoch_fence.is_stale(self._epoch, result.epoch):
            err = epoch_fence.reject("token_cb", self._epoch, result.epoch)
            log.warning(
                "zombie token for %s step %d dropped: %s",
                result.nonce, result.step, err,
            )
            return
        sent = self._sent_at.pop((result.nonce, result.step), None)
        if sent is not None:
            dt = time.monotonic() - sent
            _HOP_RTT_MS.observe(dt * 1000)
            # the API-local half of critical-path attribution: everything
            # between flush and resolve is ring time, which the stitched
            # shard spans (compute/tx) carve into finer segments when a
            # cluster timeline is available (obs/critical_path.py)
            get_recorder().span(
                result.nonce, "hop_rtt", dt * 1000, step=result.step
            )
            self._step_ema = dt if self._step_ema <= 0 else (
                0.8 * self._step_ema + 0.2 * dt
            )
        if result.error and result.error.startswith("prefix-miss:"):
            # a shard lost this snapshot — which means it restarted (or
            # diverged) and lost ALL of them, and the failed request itself
            # indexed a key no shard ever stored.  Clearing the whole index
            # self-heals in ONE failure: with the full prompt stashed, THIS
            # request re-sends a full prefill (which re-stores everywhere)
            # instead of surfacing an InferenceError; only a second miss —
            # no stash left — fails loudly.
            self._prefix_index.clear()
            state = self._refill_state.pop(result.nonce, None)
            if state is not None and result.step == 0:
                try:
                    task = asyncio.ensure_future(
                        self._refill_prefill(result.nonce, state)
                    )
                    self._refill_tasks.add(task)
                    task.add_done_callback(self._refill_tasks.discard)
                except RuntimeError:
                    # no running loop (sync caller): surface the error
                    # instead of silently dropping the request
                    log.warning("prefix refill skipped: no event loop")
                else:
                    _PREFIX_REFILL.inc()
                    log.warning(
                        "prefix refill for %s: %s", result.nonce, result.error
                    )
                    return  # the step-0 future stays pending for the refill
        elif result.step == 0:
            # the suffix prefill resolved: the stashed prompt is dead weight
            self._refill_state.pop(result.nonce, None)
        if not self._futures.resolve(result):
            if result.step <= self._granted.get(result.nonce, -1):
                # a granted step raced ahead of the driver's await: hold it
                # until send_tokens registers the future (bounded by the
                # grant window; reset_cache clears leftovers)
                self._early[(result.nonce, result.step)] = result
                return
            log.warning("unmatched token for nonce %s step %d", result.nonce, result.step)

    async def _refill_prefill(self, nonce: str, state: dict) -> None:
        """Re-drive step 0 as a FULL prefill after a shard-side prefix
        miss.  The stashed prompt replays through the normal frame path
        (grant sizing included); the shards' partially-seeded sessions are
        reset first — a healthy shard seeded its window from its snapshot
        at the prefix pos, which a pos-0 full prefill must not extend.  A
        send failure resolves the still-pending step-0 future with an
        error, so the driver fails fast instead of burning its timeout."""
        try:
            token_ids = state["token_ids"]
            await asyncio.gather(
                *(
                    c.reset_cache(nonce, epoch=self._epoch)
                    for c in self._shard_clients.values()
                ),
                return_exceptions=True,
            )
            pos = self._pos_for(nonce, 0, len(token_ids))
            prefix_store = ""
            if self._prefix_cap > 0 and len(token_ids) >= self.PREFIX_MIN_TOKENS:
                # re-index under a fresh key: the miss cleared the whole
                # index, and this full prefill re-stores on every shard
                prefix_store = self._prefix_put(tuple(token_ids))
            get_recorder().span(
                nonce, "prefix_refill", 0.0, tokens=len(token_ids)
            )
            await self._send_token_frame(
                nonce, token_ids, pos, state["decoding"], 0, state["budget"],
                prefix_store=prefix_store,
            )
        except Exception as exc:
            log.exception("prefix refill for %s failed", nonce)
            self._futures.resolve(
                TokenResult(
                    nonce=nonce, token_id=-1, step=0,
                    error=f"prefix refill failed: {exc}",
                )
            )

    async def _idle_sweep(self) -> None:
        while True:
            await asyncio.sleep(self._stream_idle_s)
            if self._streams is not None:
                await self._streams.cleanup_idle()


class ApiTokenServicer:
    """gRPC ShardApi service: receives the sampled token from the end shard."""

    def __init__(self, resolve: Callable[[TokenResult], None]) -> None:
        self._resolve = resolve

    async def send_token(self, payload: TokenPayload, context) -> Empty:
        self._resolve(payload.to_result())
        return Empty()
