"""Execution-strategy seam: how the API node reaches the compute.

`ApiAdapterBase` is the contract the decode driver speaks
(reference: src/dnet/api/strategies/base.py:7-54).  Implementations:

- `LocalAdapter` (here): single-process — the model runs in this process on
  the local JAX device(s); the "ring" is a thread-pool call.
- `RingApiAdapter` (dnet_tpu/api/ring.py, task of the two-role split):
  gRPC streaming to the first shard + token-callback futures.

Because both speak the same surface, InferenceManager and the HTTP layer are
identical for 1 chip and for a multi-host ring.
"""

from __future__ import annotations

import abc
import asyncio
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.core.types import DecodingParams, TokenResult
from dnet_tpu.utils.logger import get_logger


async def _embed_on_executor(hidden_fn, executor, ids_list):
    """Mean-pool hidden_states per input on the adapter's compute executor
    (session bookkeeping must not race concurrent decode steps)."""
    import numpy as np

    loop = asyncio.get_running_loop()
    out: List[List[float]] = []
    for ids in ids_list:
        h = await loop.run_in_executor(executor, hidden_fn, ids)  # [T, D]
        out.append([float(v) for v in np.mean(h, axis=0)])
    return out

log = get_logger()

# bound on awaiting a cancelled background task at shutdown: a step wedged
# in run_in_executor defers cancellation until the executor job completes,
# which for a wedged device dispatch is never — shutdown must not hang on it
_REAP_TIMEOUT_S = 5.0


async def _reap(task: Optional["asyncio.Task"], what: str) -> None:
    """Cancel-and-await a background task, bounded: the dropped-cancellation
    fix (the runtime twin of DL003) without trading it for an unbounded
    shutdown hang.  On timeout the task is abandoned with a warning — the
    same contract as a compute thread that fails to join."""
    if not task:
        return
    task.cancel()
    try:
        await asyncio.wait_for(task, timeout=_REAP_TIMEOUT_S)
    except (asyncio.CancelledError, asyncio.TimeoutError):
        pass
    if not task.done():
        log.warning(
            "%s ignored cancellation for %.0fs at shutdown; abandoning it "
            "(likely wedged in an executor step)", what, _REAP_TIMEOUT_S,
        )


class ApiAdapterBase(abc.ABC):
    """Token-path adapter between the decode driver and the compute plane."""

    @abc.abstractmethod
    async def start(self) -> None: ...

    @abc.abstractmethod
    async def shutdown(self) -> None: ...

    @abc.abstractmethod
    async def reset_cache(self, nonce: str) -> None:
        """Drop per-nonce state (KV) wherever it lives."""

    @abc.abstractmethod
    async def send_tokens(
        self,
        nonce: str,
        token_ids: List[int],
        decoding: DecodingParams,
        step: int,
        budget: Optional[int] = None,
    ) -> None:
        """Inject tokens for one decode step (whole prompt on step 0).

        `budget` is the driver's remaining token allowance for the request —
        a hint adapters may use to fuse multiple decode steps into one device
        program (chunked decode) without overshooting max_tokens."""

    @abc.abstractmethod
    async def await_token(self, nonce: str, step: int, timeout: float) -> TokenResult:
        """Wait for the sampled token of a specific step to come back."""

    def resolve_token(self, result: TokenResult) -> None:
        """Called by the transport when a token arrives (default: no-op)."""

    def set_deadline(self, nonce: str, deadline_ts: float) -> None:
        """Register the request's absolute wall-clock deadline (epoch
        seconds).  Adapters that serialize frames stamp it into every
        frame header so downstream hops can shed expired work
        (dnet_tpu/admission/).  Local adapters need no stamp — the driver
        itself checks between steps — so the default is a no-op."""

    def fail_pending(self, error: str) -> None:
        """Fail every in-flight token wait with `error` (fast-fail on shard
        death — the failure monitor calls this instead of letting requests
        burn the full await_token timeout).  The default covers any adapter
        built on `_TokenFutures`; adapters with different bookkeeping
        override."""
        futures = getattr(self, "_futures", None)
        if isinstance(futures, _TokenFutures):
            futures.fail_all(error)

    def max_seq(self) -> Optional[int]:
        """Sequence capacity of the serving path, when known."""
        return None

    async def embed(self, ids_list: List[List[int]]) -> List[List[float]]:
        """Mean-pooled final-hidden-state embeddings, one vector per input
        (beyond the reference, which never serves /v1/embeddings).
        Default: unsupported — the gRPC ring's shards never ship hidden
        states back to the API node.  The local adapter serves it for
        Local AND Mesh engines (both expose hidden_states), the batched
        adapter via its inner engine."""
        raise NotImplementedError(
            f"embeddings unsupported on {type(self).__name__}"
        )


class _TokenFutures:
    """Per-nonce, step-keyed future map shared by adapter implementations.

    Futures are keyed by (nonce, step) so a late token from a timed-out step
    can never be delivered to a later step of the same request.  resolve()
    may be called from any thread; it never pops — the awaiting side owns
    cleanup (pop happens in await_token's finally), which closes the race
    where a fast compute thread resolved before await_token looked up the
    future.  Reference: RingApiAdapter.await_token/resolve_token
    (src/dnet/api/strategies/ring.py:198-209).
    """

    def __init__(self) -> None:
        self._futures: Dict[tuple[str, int], asyncio.Future] = {}

    def expect(self, nonce: str, step: int) -> asyncio.Future:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._futures[(nonce, step)] = fut
        return fut

    def resolve(self, result: TokenResult) -> bool:
        fut = self._futures.get((result.nonce, result.step))
        if fut is None or fut.done():
            return False
        fut.get_loop().call_soon_threadsafe(
            lambda: fut.done() or fut.set_result(result)
        )
        return True

    async def wait(self, nonce: str, step: int, timeout: float) -> TokenResult:
        fut = self._futures.get((nonce, step))
        if fut is None:
            raise RuntimeError(f"no pending token for nonce {nonce} step {step}")
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._futures.pop((nonce, step), None)

    def cancel_nonce(self, nonce: str) -> None:
        for key in [k for k in self._futures if k[0] == nonce]:
            fut = self._futures.pop(key)
            if not fut.done():
                fut.cancel()

    def fail_all(self, error: str) -> None:
        """Resolve every pending future with an error TokenResult (the
        awaiting side still owns the pop)."""
        for (nonce, step) in list(self._futures):
            self.resolve(
                TokenResult(nonce=nonce, token_id=-1, step=step, error=error)
            )


class BatchedLocalAdapter(ApiAdapterBase):
    """Continuous-batching strategy over a BatchedEngine.

    Decode steps from concurrent requests coalesce: send_tokens enqueues the
    step and a scheduler task drains everything pending into ONE batched
    engine call (core/batch.py).  While a batched step runs on the compute
    executor, newly arriving steps queue for the next round — classic
    continuous batching.  Prefills run between batched steps on the same
    executor (no KV races: one compute thread)."""

    PREFILL_CHUNK = 256  # prompt tokens per executor job (interleave grain)

    def __init__(self, engine) -> None:
        from dnet_tpu.config import get_settings

        self.engine = engine  # BatchedEngine
        self._futures = _TokenFutures()
        self._executor: Optional[ThreadPoolExecutor] = None
        self._pending: Dict[str, tuple] = {}  # nonce -> (token, decoding, step)
        self._kick: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._prefill_tasks: set = set()
        # DNET_FLEET_DECODE_PACE_MS: floor wall-clock per batched step,
        # emulating device-bound decode where the host waits on the
        # accelerator instead of owning the core (config.FleetSettings)
        self._pace_s = (
            max(get_settings().fleet.fleet_decode_pace_ms, 0.0) / 1000.0
        )

    SWEEP_INTERVAL_S = 60.0

    async def start(self) -> None:
        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="compute")
        self._kick = asyncio.Event()
        self._task = asyncio.ensure_future(self._batch_loop())
        self._sweep_task = asyncio.ensure_future(self._sweep_loop())

    async def _sweep_loop(self) -> None:
        """Periodic TTL sweep on the compute thread: a client that vanished
        without reset_cache must not pin its slot forever (at capacity the
        pool would reject every new request)."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.SWEEP_INTERVAL_S)
            if self._executor is None:
                return
            try:
                n = await loop.run_in_executor(
                    self._executor, self.engine.sweep_sessions
                )
                if n:
                    log.info("TTL sweep freed %d idle sessions", n)
            except Exception:
                log.exception("session sweep failed")

    async def shutdown(self) -> None:
        # cancel AND await (bounded): a dropped cancellation leaves the
        # task to die unobserved at loop close — and a sweep mid-
        # run_in_executor would keep touching the engine after the
        # executor below is gone
        task, self._task = self._task, None
        await _reap(task, "batch loop")
        sweep, self._sweep_task = getattr(self, "_sweep_task", None), None
        await _reap(sweep, "session sweep")
        for t in list(self._prefill_tasks):
            t.cancel()
        self._prefill_tasks.clear()
        if self._executor:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None

    async def reset_cache(self, nonce: str) -> None:
        self._pending.pop(nonce, None)
        # slot state is owned by the compute thread: freeing it from the
        # event loop would race an in-flight batched step
        if self._executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                self._executor, self.engine.end_session, nonce
            )
        self._futures.cancel_nonce(nonce)

    def max_seq(self) -> Optional[int]:
        return self.engine.max_seq

    async def embed(self, ids_list: List[List[int]]) -> List[List[float]]:
        # the inner engine produces the hidden states (BatchedEngine wraps a
        # LocalEngine as .eng, PipelinedMeshEngine a MeshEngine as ._inner);
        # the batched programs themselves only decode
        inner = getattr(self.engine, "eng", None) or getattr(
            self.engine, "_inner", None
        )
        fn = getattr(inner, "hidden_states", None)
        if fn is None:
            raise NotImplementedError(
                f"embeddings unsupported on {type(self.engine).__name__}"
            )
        return await _embed_on_executor(fn, self._executor, ids_list)

    async def send_tokens(
        self,
        nonce: str,
        token_ids: List[int],
        decoding: DecodingParams,
        step: int,
        budget: Optional[int] = None,
    ) -> None:
        if self._executor is None or self._kick is None:
            raise RuntimeError("adapter not started")
        self._futures.expect(nonce, step)
        if step == 0:
            if hasattr(self.engine, "prefill_chunk"):
                # chunked prefill: one executor job per chunk, so queued
                # batched decode steps run BETWEEN chunks — a long prompt
                # stalls active lanes for at most one chunk's prefill.
                # (PipelinedMeshEngine has no prefill_chunk: its prefill is
                # a single ring pass, the single-shot fallback below.)
                task = asyncio.ensure_future(
                    self._prefill_chunked(nonce, list(token_ids), decoding, step)
                )
                self._prefill_tasks.add(task)
                task.add_done_callback(self._prefill_tasks.discard)
            else:
                loop = asyncio.get_running_loop()
                loop.run_in_executor(
                    self._executor, self._prefill, nonce, list(token_ids),
                    decoding, step,
                )
        elif nonce not in self.engine.sessions:
            # mid-generation session loss: fail fast instead of silently
            # re-prefilling from the single last sampled token
            self._futures.resolve(
                TokenResult(
                    nonce=nonce, token_id=-1,
                    error=f"session expired for request {nonce}", step=step,
                )
            )
        else:
            self._pending[nonce] = (token_ids[-1], decoding, step, budget)
            self._kick.set()

    def _prefill(self, nonce: str, ids: List[int], decoding: DecodingParams, step: int) -> None:
        try:
            res = self.engine.prefill_and_sample(nonce, ids, decoding)
            self._futures.resolve(
                self.engine.token_result(nonce, res, step=step, decoding=decoding)
            )
        except Exception as exc:
            log.exception("batched prefill failed")
            self._futures.resolve(
                TokenResult(nonce=nonce, token_id=-1, error=str(exc), step=step)
            )

    def _cancelled(self, nonce: str, step: int) -> bool:
        return (nonce, step) not in self._futures._futures

    async def _prefill_chunked(
        self, nonce: str, ids: List[int], decoding: DecodingParams, step: int
    ) -> None:
        loop = asyncio.get_running_loop()
        eng = self.engine
        try:
            # claim a batch slot BEFORE burning any prefill compute (a full
            # pool must fail instantly, not after the whole prompt)
            await loop.run_in_executor(self._executor, eng.reserve_slot, nonce)
            # prefix cache first: a chunked prefill must look up the FULL
            # prompt, then prefill only the uncached suffix
            n = await loop.run_in_executor(
                self._executor, eng.seed_from_prefix, nonce, ids, decoding.seed
            )
            rest = ids[n:]
            logits = None
            for i in range(0, len(rest), self.PREFILL_CHUNK):
                if self._cancelled(nonce, step):
                    await loop.run_in_executor(
                        self._executor, eng.abandon_prefill, nonce
                    )
                    return
                chunk = rest[i : i + self.PREFILL_CHUNK]
                logits = await loop.run_in_executor(
                    self._executor, eng.prefill_chunk, nonce, chunk, decoding.seed
                )
            await loop.run_in_executor(
                self._executor, eng.store_prefix, nonce, ids
            )
            if self._cancelled(nonce, step):
                await loop.run_in_executor(self._executor, eng.abandon_prefill, nonce)
                return
            res = await loop.run_in_executor(
                self._executor, eng.adopt_prefilled, nonce, logits, decoding
            )
            self._futures.resolve(
                eng.token_result(nonce, res, step=step, decoding=decoding)
            )
        except Exception as exc:
            log.exception("chunked batched prefill failed")
            try:
                await loop.run_in_executor(self._executor, eng.abandon_prefill, nonce)
            except Exception as exc:  # executor already shut down
                log.debug("abandon_prefill skipped for %s: %s", nonce, exc)
            self._futures.resolve(
                TokenResult(nonce=nonce, token_id=-1, error=str(exc), step=step)
            )

    async def _batch_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            await self._kick.wait()
            self._kick.clear()
            await asyncio.sleep(0)  # coalesce: let concurrent senders enqueue
            pending, self._pending = self._pending, {}
            if not pending:
                continue
            t0 = loop.time()
            await loop.run_in_executor(self._executor, self._batched_step, pending)
            if self._pace_s > 0.0:
                # device-bound emulation: a batched step may not complete
                # faster than the pace floor.  The wait is loop-yielding,
                # so co-hosted replicas overlap their floors — unlike the
                # compute itself, which serializes on the CPU.
                remain = self._pace_s - (loop.time() - t0)
                if remain > 0.0:
                    await asyncio.sleep(remain)

    async def await_token(self, nonce: str, step: int, timeout: float) -> TokenResult:
        return await self._futures.wait(nonce, step, timeout)

    def resolve_token(self, result: TokenResult) -> None:
        self._futures.resolve(result)

    def _batched_step(self, pending: Dict[str, tuple]) -> None:
        try:
            reqs = {n: (tok, dec) for n, (tok, dec, _step, _b) in pending.items()}
            # budgets widen the dispatch where the engine supports fused
            # multi-rotation chunks (PipelinedMeshEngine): extras buffer
            # engine-side and resolve later steps without a dispatch
            budgets = {n: b for n, (_t, _d, _s, b) in pending.items()}
            results, errors = self.engine.decode_batch(reqs, budgets=budgets)
        except Exception as exc:
            log.exception("batched decode step failed")
            for nonce, (_tok, _dec, step, _b) in pending.items():
                self._futures.resolve(
                    TokenResult(nonce=nonce, token_id=-1, error=str(exc), step=step)
                )
            return
        for nonce, res in results.items():
            _tok, dec, step, _b = pending[nonce]
            self._futures.resolve(
                self.engine.token_result(nonce, res, step=step, decoding=dec)
            )
        for nonce, msg in errors.items():
            _tok, _dec, step, _b = pending[nonce]
            self._futures.resolve(
                TokenResult(nonce=nonce, token_id=-1, error=msg, step=step)
            )


class LocalAdapter(ApiAdapterBase):
    """Single-process strategy: the engine *is* the ring.

    Compute runs on a dedicated single-thread executor (the analog of the
    shard's dedicated compute thread, src/dnet/shard/runtime.py:364-372), so
    the event loop never blocks on XLA.

    Decode steps are CHUNKED when the engine supports it: one engine call
    fuses up to `chunk_size` steps on-device (LocalEngine.decode_chunk) and
    the extra tokens are buffered here, resolving later send_tokens calls
    instantly — the driver's per-token protocol is unchanged, but the device
    round-trip cost is paid once per chunk.  Chunk width RAMPS 2 -> 4 -> ...
    -> chunk_size per request, so streaming clients see early tokens at
    per-token latency while long generations converge to fused throughput.
    """

    MAX_BUFFERED_NONCES = 64  # aborted-mid-chunk leftovers cap (leak bound)

    def __init__(self, engine, chunk_size: int = 32) -> None:
        self.engine = engine
        self.chunk_size = max(1, chunk_size)
        self._futures = _TokenFutures()
        self._executor: Optional[ThreadPoolExecutor] = None
        # nonce -> {step: TokenResult}; guarded by _buf_lock (compute thread
        # inserts, event loop consumes/clears).  The guarded-by contract is
        # declared in analysis/runtime/domains.py and enforced under
        # DNET_SAN=1; with it unset these are the plain dicts/lock.
        self._buf_lock = dsan.san_lock("LocalAdapter._buf_lock")
        _buf_dom = dsan.maybe_lock_domain(self._buf_lock)
        self._buffered: Dict[str, Dict[int, TokenResult]] = dsan.guard_dict(
            {}, _buf_dom, "LocalAdapter._buffered"
        )
        self._ramp: Dict[str, int] = dsan.guard_dict(
            {}, _buf_dom, "LocalAdapter._ramp"
        )  # nonce -> next chunk width

    SWEEP_INTERVAL_S = 60.0
    # same periodic TTL sweep as the batched adapter (one implementation)
    _sweep_loop = BatchedLocalAdapter._sweep_loop

    async def start(self) -> None:
        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="compute")
        self._sweep_task = asyncio.ensure_future(self._sweep_loop())

    async def shutdown(self) -> None:
        # same bounded dropped-cancellation fix as the batched adapter:
        # await the cancelled sweep so it cannot touch the engine past
        # executor teardown or die unobserved at loop close
        sweep, self._sweep_task = getattr(self, "_sweep_task", None), None
        await _reap(sweep, "session sweep")
        if self._executor:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None

    async def reset_cache(self, nonce: str) -> None:
        self.engine.end_session(nonce)
        self._futures.cancel_nonce(nonce)
        with self._buf_lock:
            self._buffered.pop(nonce, None)
            self._ramp.pop(nonce, None)

    def max_seq(self) -> Optional[int]:
        return self.engine.max_seq

    async def embed(self, ids_list: List[List[int]]) -> List[List[float]]:
        fn = getattr(self.engine, "hidden_states", None)
        if fn is None:
            raise NotImplementedError(
                f"embeddings unsupported on {type(self.engine).__name__}"
            )
        return await _embed_on_executor(fn, self._executor, ids_list)

    async def send_tokens(
        self,
        nonce: str,
        token_ids: List[int],
        decoding: DecodingParams,
        step: int,
        budget: Optional[int] = None,
    ) -> None:
        if self._executor is None:
            raise RuntimeError("adapter not started")
        self._futures.expect(nonce, step)
        with self._buf_lock:
            entries = self._buffered.get(nonce)
            buffered = entries.pop(step, None) if entries else None
            if entries is not None and not entries:
                del self._buffered[nonce]  # drained: don't count toward the cap
        if buffered is not None:
            self._futures.resolve(buffered)
            return
        loop = asyncio.get_running_loop()
        loop.run_in_executor(
            self._executor,
            self._compute_step, nonce, list(token_ids), decoding, step, budget,
        )

    def _next_chunk_width(self, nonce: str, budget: Optional[int]) -> int:
        with self._buf_lock:
            width = self._ramp.get(nonce, min(2, self.chunk_size))
            self._ramp[nonce] = min(width * 2, self.chunk_size)
            if len(self._ramp) > self.MAX_BUFFERED_NONCES:
                # entries re-created by a compute step racing reset_cache
                # (aborted request) have no session and can be pruned
                live = self.engine.sessions
                for n in [n for n in self._ramp if n not in live]:
                    del self._ramp[n]
        # no budget => no chunking: a chunk must never overshoot max_tokens
        # by more than the driver is prepared to discard
        return min(width, budget) if budget is not None else 1

    def _chunked_results(
        self,
        eng,
        nonce: str,
        token_ids: List[int],
        decoding,
        budget: Optional[int],
    ):
        """Pipelined chunked decode: read the current chunk AFTER dispatching
        the next one, so the result transfer (and this thread's host work)
        overlaps the device computing ahead.  The next chunk chains from the
        device-resident last token — no host round trip feeds the device.

        Returns the current chunk's SampleResults, or None to fall back to
        per-token decode (engine without chunk support / width-1 budget).
        """
        if (
            budget is not None
            and budget > 1
            and getattr(eng, "spec_eligible", None) is not None
            and eng.spec_eligible(decoding)
            and eng.spec_worthwhile(nonce)
            and eng.pending_chunks(nonce) == 0
        ):
            # speculative path: one verify forward emits 1..L+1 greedy-exact
            # tokens; the per-token driver protocol is unchanged (extras are
            # buffered exactly like chunked results)
            return eng.decode_spec(nonce, token_ids[-1], decoding, budget)
        if not hasattr(eng, "decode_chunk_dispatch"):
            # legacy engines: one-shot chunk call, no pipelining
            chunk = self._next_chunk_width(nonce, budget)
            if chunk > 1 and hasattr(eng, "decode_chunk"):
                return eng.decode_chunk(nonce, token_ids[-1], decoding, chunk)
            return None
        if eng.pending_chunks(nonce) == 0:
            chunk = self._next_chunk_width(nonce, budget)
            if chunk <= 1:
                return None
            if eng.decode_chunk_dispatch(nonce, token_ids[-1], decoding, chunk) == 0:
                return None
        # speculate one chunk beyond the unread one while we block on the
        # read; EOS overshoot wastes at most that chunk's compute (its KV
        # rows die with the session, same as the in-chunk overshoot)
        if budget is not None and budget - eng.pending_width(nonce) > 1:
            nxt = self._next_chunk_width(nonce, budget - eng.pending_width(nonce))
            if nxt > 1:
                eng.decode_chunk_dispatch(nonce, None, decoding, nxt)
        return eng.decode_chunk_read(nonce)

    def _buffer_results(self, nonce: str, entries: Dict[int, TokenResult]) -> None:
        with self._buf_lock:
            self._buffered[nonce] = entries
            if len(self._buffered) > self.MAX_BUFFERED_NONCES:
                # leftovers of aborted requests (session already ended) are
                # the only entries that can accumulate — never evict a live
                # request's pending tokens, that would corrupt its stream
                live = self.engine.sessions
                for n in [n for n in self._buffered if n not in live]:
                    if len(self._buffered) <= self.MAX_BUFFERED_NONCES:
                        break
                    del self._buffered[n]

    def _compute_step(
        self,
        nonce: str,
        token_ids: List[int],
        decoding: DecodingParams,
        step: int,
        budget: Optional[int] = None,
    ) -> None:
        try:
            eng = self.engine
            if step == 0:
                res = eng.prefill_and_sample(nonce, token_ids, decoding)
            elif nonce not in eng.sessions:
                # mid-generation session loss (TTL sweep / reset race) is an
                # error: re-prefilling from the single last token would
                # silently continue with empty context
                raise RuntimeError(f"session expired for request {nonce}")
            else:
                results = self._chunked_results(eng, nonce, token_ids, decoding, budget)
                if results is None:
                    res = eng.decode_step(nonce, token_ids[-1], decoding)
                else:
                    if len(results) > 1:
                        self._buffer_results(
                            nonce,
                            {
                                step + i: eng.token_result(
                                    nonce, r, step=step + i, decoding=decoding
                                )
                                for i, r in enumerate(results[1:], start=1)
                            },
                        )
                    res = results[0]
            result = eng.token_result(nonce, res, step=step, decoding=decoding)
            self._futures.resolve(result)
        except Exception as exc:  # surfaced to await_token as an error result
            log.exception("local compute step failed")
            self._futures.resolve(
                TokenResult(nonce=nonce, token_id=-1, error=str(exc), step=step)
            )

    async def await_token(self, nonce: str, step: int, timeout: float) -> TokenResult:
        return await self._futures.wait(nonce, step, timeout)

    def resolve_token(self, result: TokenResult) -> None:
        self._futures.resolve(result)
