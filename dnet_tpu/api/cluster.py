"""ClusterManager: discovery, shard health, profiling, topology state.

Reference: src/dnet/api/cluster.py:32-276.  Grows with the two-role split
(health/latency/profile fan-out) and the solver (profile_cluster); today it
owns the device table and the current topology.
"""

from __future__ import annotations

from typing import List, Optional  # noqa: F401

import httpx

from dnet_tpu.core.types import DeviceInfo, TopologyInfo
from dnet_tpu.membership import EpochClock, set_epoch_gauge
from dnet_tpu.utils.logger import get_logger

log = get_logger()


class ClusterManager:
    def __init__(self, discovery) -> None:
        self.discovery = discovery
        self.current_topology: Optional[TopologyInfo] = None
        # instance -> measured/predicted stage-time ratio (calibration loop)
        self.stage_ratios: dict = {}
        # membership epoch mint (dnet_tpu/membership/): every INSTALLED
        # topology gets a strictly larger epoch — the fencing token the
        # load fan-out pins on each shard
        self.epoch_clock = EpochClock()

    @property
    def epoch(self) -> int:
        """Epoch of the currently installed topology (0 = none)."""
        topo = self.current_topology
        return topo.epoch if topo is not None else 0

    def install_topology(self, topo: TopologyInfo) -> TopologyInfo:
        """Mint a fresh epoch for `topo` and make it current.  THE way a
        solved/manual topology becomes active — direct assignment to
        `current_topology` skips the mint and leaves the ring unfenced
        (tests only)."""
        self.epoch_clock.observe(topo.epoch)
        topo.epoch = self.epoch_clock.mint()
        self.current_topology = topo
        log.info(
            "topology installed: epoch %d over %d shard(s)",
            topo.epoch, len(topo.assignments),
        )
        return topo

    def restore_topology(self, topo: Optional[TopologyInfo]) -> None:
        """Roll back to a previously installed topology (failed reload):
        its already-minted epoch becomes current again — the aborted
        epoch is burned, never reused."""
        self.current_topology = topo
        set_epoch_gauge(topo.epoch if topo is not None else 0)

    async def scan_devices(self) -> List[DeviceInfo]:
        # manager (API) nodes are not compute shards
        return [d for d in self.discovery.peers() if not d.is_manager]

    async def healthy_devices(self, timeout_s: float = 5.0) -> List[DeviceInfo]:
        """Parallel health checks; unhealthy shards are filtered before any
        solve (reference: api/cluster.py:66-109)."""
        import asyncio

        devices = await self.scan_devices()

        async def check(d: DeviceInfo) -> Optional[DeviceInfo]:
            url = f"http://{d.host}:{d.http_port}/health"
            try:
                async with httpx.AsyncClient(timeout=timeout_s) as client:
                    r = await client.get(url)
                    if r.status_code == 200:
                        return d
            except httpx.HTTPError:
                pass
            log.warning("shard %s unhealthy (%s)", d.instance, url)
            return None

        results = await asyncio.gather(*(check(d) for d in devices))
        return [d for d in results if d is not None]

    async def profile_cluster(
        self, payload_sizes: Optional[List[int]] = None, timeout_s: float = 300.0
    ) -> List[DeviceInfo]:
        """Health-filter -> parallel /profile -> /measure_latency between ring
        neighbors -> merged DeviceInfo list (reference api/cluster.py:38-244)."""
        import asyncio

        devices = await self.healthy_devices()
        if not devices:
            return []
        payload_sizes = payload_sizes or [65536, 1048576]

        async with httpx.AsyncClient(timeout=timeout_s) as client:

            async def profile_one(d: DeviceInfo) -> None:
                url = f"http://{d.host}:{d.http_port}/profile"
                try:
                    r = await client.post(url, json={})
                    r.raise_for_status()
                    p = r.json()["profile"]
                    d.flops_bf16 = p.get("flops_bf16", 0.0)
                    d.hbm_bw = p.get("hbm_bw", 0.0)
                    d.host_to_hbm_bw = p.get("host_to_hbm_bw", 0.0)
                    d.hbm_bytes = p.get("hbm_bytes", 0) or d.hbm_bytes
                    d.host_ram_bytes = p.get("host_ram_bytes", 0)
                    d.chip_kind = p.get("device_kind", d.chip_kind)
                    d.chip_count = p.get("local_device_count", 0) or d.chip_count
                except (httpx.HTTPError, KeyError) as exc:
                    log.warning("profile of %s failed: %s", d.instance, exc)

            await asyncio.gather(*(profile_one(d) for d in devices))

            async def latency_one(d: DeviceInfo, peer: DeviceInfo) -> None:
                url = f"http://{d.host}:{d.http_port}/measure_latency"
                body = {
                    "peers": [f"{peer.host}:{peer.grpc_port}"],
                    "payload_sizes": payload_sizes,
                    "rounds": 3,
                }
                try:
                    r = await client.post(url, json=body)
                    r.raise_for_status()
                    lat = r.json()["latency"]
                    per_size = next(iter(lat.values()), {})
                    if per_size:
                        # median across payload sizes ~ solver's t_comm
                        vals = sorted(per_size.values())
                        d.t_comm = vals[len(vals) // 2]
                except (httpx.HTTPError, KeyError) as exc:
                    log.warning("latency probe from %s failed: %s", d.instance, exc)

            await asyncio.gather(
                *(
                    latency_one(d, devices[(i + 1) % len(devices)])
                    for i, d in enumerate(devices)
                    if len(devices) > 1
                )
            )
        return devices

    async def calibrate_topology(
        self, steps: int = 3, timeout_s: float = 120.0
    ) -> list:
        """Close the solver's prediction loop: probe every loaded shard's
        REAL per-token stage time (/probe_stage) and join it with the
        predictions recorded at solve time.  Returns StageCalibration rows
        (parallel/calibrate.py); the caller may feed them to recalibrate()
        and re-solve with corrected device speeds.  The reference never
        validates its cost model against reality (SURVEY.md §2.7)."""
        import asyncio

        from dnet_tpu.parallel.calibrate import compare, log_table

        topo = self.current_topology
        if topo is None:
            raise ValueError("no topology loaded")
        by_instance = {d.instance: d for d in topo.devices}
        measured: dict = {}

        async with httpx.AsyncClient(timeout=timeout_s) as client:

            async def probe_one(instance: str) -> None:
                d = by_instance.get(instance)
                if d is None:
                    return
                url = f"http://{d.host}:{d.http_port}/probe_stage?steps={steps}"
                try:
                    r = await client.post(url)
                    r.raise_for_status()
                    measured[instance] = float(r.json()["stage_time_s"])
                except (httpx.HTTPError, KeyError, ValueError) as exc:
                    log.warning("stage probe of %s failed: %s", instance, exc)

            await asyncio.gather(
                *(probe_one(a.instance) for a in topo.assignments)
            )
        cals = compare(topo, measured)
        log_table(cals)
        return cals

    # total correction is bounded even across repeated calibrations
    _RATIO_TOTAL_CLAMP = (1 / 16, 16.0)

    def store_stage_ratios(self, cals: list) -> None:
        """Remember measured/predicted ratios so future solves use observed,
        not estimated, per-device speed.  A new ratio COMPOSES with the one
        already applied: after a first correction the next solve's
        predictions are made with corrected speeds, so a follow-up
        calibration measuring ~1.0 means "the stored correction is right",
        not "no correction needed" — overwriting would oscillate."""
        from dnet_tpu.parallel.calibrate import RATIO_CLAMP

        lo, hi = self._RATIO_TOTAL_CLAMP
        for c in cals:
            if c.predicted_s > 0 and c.measured_s > 0:
                step = min(max(c.ratio, RATIO_CLAMP[0]), RATIO_CLAMP[1])
                total = self.stage_ratios.get(c.instance, 1.0) * step
                self.stage_ratios[c.instance] = min(max(total, lo), hi)

    def apply_stage_ratios(self, devices: List[DeviceInfo]) -> List[DeviceInfo]:
        """Return copies of freshly profiled devices with speeds scaled by
        the stored calibration ratios (ratio r = device ran r times slower
        than its profile).  Copies, not in-place: discovery may hand out the
        same DeviceInfo objects on every scan, and a failed re-profile would
        otherwise compound the division across solves."""
        from dataclasses import replace as dc_replace

        out: List[DeviceInfo] = []
        for d in devices:
            r = self.stage_ratios.get(d.instance)
            if r:
                d = dc_replace(
                    d,
                    flops_bf16=d.flops_bf16 / r,
                    hbm_bw=d.hbm_bw / r,
                    host_to_hbm_bw=d.host_to_hbm_bw / r,
                )
            out.append(d)
        return out

    def head_device(self) -> Optional[DeviceInfo]:
        """Owner of layer 0 in the current topology."""
        if self.current_topology is None:
            return None
        head = self.current_topology.head_instance()
        for d in self.current_topology.devices:
            if d.instance == head:
                return d
        return None
