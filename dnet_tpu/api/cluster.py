"""ClusterManager: discovery, shard health, profiling, topology state.

Reference: src/dnet/api/cluster.py:32-276.  Grows with the two-role split
(health/latency/profile fan-out) and the solver (profile_cluster); today it
owns the device table and the current topology.
"""

from __future__ import annotations

from typing import List, Optional

import httpx

from dnet_tpu.core.types import DeviceInfo, TopologyInfo
from dnet_tpu.utils.logger import get_logger

log = get_logger()


class ClusterManager:
    def __init__(self, discovery) -> None:
        self.discovery = discovery
        self.current_topology: Optional[TopologyInfo] = None

    async def scan_devices(self) -> List[DeviceInfo]:
        return list(self.discovery.peers())

    async def healthy_devices(self, timeout_s: float = 5.0) -> List[DeviceInfo]:
        """Parallel health checks; unhealthy shards are filtered before any
        solve (reference: api/cluster.py:66-109)."""
        import asyncio

        devices = await self.scan_devices()

        async def check(d: DeviceInfo) -> Optional[DeviceInfo]:
            url = f"http://{d.host}:{d.http_port}/health"
            try:
                async with httpx.AsyncClient(timeout=timeout_s) as client:
                    r = await client.get(url)
                    if r.status_code == 200:
                        return d
            except httpx.HTTPError:
                pass
            log.warning("shard %s unhealthy (%s)", d.instance, url)
            return None

        results = await asyncio.gather(*(check(d) for d in devices))
        return [d for d in results if d is not None]

    def head_device(self) -> Optional[DeviceInfo]:
        """Owner of layer 0 in the current topology."""
        if self.current_topology is None:
            return None
        head = self.current_topology.head_instance()
        for d in self.current_topology.devices:
            if d.instance == head:
                return d
        return None
