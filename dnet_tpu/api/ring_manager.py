"""Ring-mode model lifecycle: per-shard /load_model fan-out + ring wiring.

Reference: src/dnet/api/model_manager.py:54-255 and the manual-topology
post-processing in src/dnet/api/http_api.py:305-403 / api/utils.py:62-131.
"""

from __future__ import annotations

import asyncio
import functools
import time
from typing import List, Optional

import httpx

from dnet_tpu.api.model_manager import resolve_model_dir
from dnet_tpu.core.types import DeviceInfo, LayerAssignment, TopologyInfo
from dnet_tpu.membership import body_signature, split_delta
from dnet_tpu.utils.logger import get_logger
from dnet_tpu.utils.tokenizer import load_tokenizer

log = get_logger()


@functools.lru_cache(maxsize=256)
def _resolve_host_cached(host: str) -> str:
    import socket

    try:
        return socket.gethostbyname(host)
    except OSError:
        return host


def _contiguous_runs(layers: List[int]) -> List[List[int]]:
    runs: List[List[int]] = []
    for a in layers:
        if runs and a == runs[-1][-1] + 1:
            runs[-1].append(a)
        else:
            runs.append([a])
    return runs


def build_manual_topology(
    model: str,
    num_layers: int,
    assignments: List[dict],
    devices: List[DeviceInfo],
    kv_bits: int = 0,
) -> TopologyInfo:
    """Order assignments into a ring by min layer, set next pointers, and
    validate full contiguous coverage (reference http_api.py:305-403)."""
    by_instance = {d.instance: d for d in devices}
    las: List[LayerAssignment] = []
    for a in assignments:
        if a["instance"] not in by_instance:
            raise ValueError(f"unknown instance {a['instance']!r}")
        if not a["layers"]:
            raise ValueError(f"empty layer list for {a['instance']!r}")
        las.append(
            LayerAssignment(
                instance=a["instance"],
                layers=sorted(a["layers"]),
                window_size=a.get("window_size", 0),
                residency_size=a.get("residency_size", 0),
                mesh_tp=a.get("mesh_tp", 0),
                mesh_sp=a.get("mesh_sp", 0),
                tp_degree=a.get("tp_degree", 0),
            )
        )
    las.sort(key=lambda a: a.min_layer)
    covered = [l for a in las for l in a.layers]
    if sorted(covered) != list(range(num_layers)):
        raise ValueError(
            f"assignments must cover layers 0..{num_layers - 1} exactly once; "
            f"got {sorted(covered)}"
        )
    # non-contiguous assignments are k-round schedules: each contiguous run
    # is one ring visit (shard/compute.py:_process_round); frames for a
    # layer a shard doesn't own relay along the ring's next pointers, so
    # exact coverage is the only structural requirement
    for a in las:
        a.rounds = _contiguous_runs(a.layers)
    for i, a in enumerate(las):
        a.next_instance = las[(i + 1) % len(las)].instance
    used = [by_instance[a.instance] for a in las]
    return TopologyInfo(
        model=model,
        num_layers=num_layers,
        kv_bits=kv_bits,
        devices=used,
        assignments=las,
    )


class RingModelManager:
    """Drives shard /load_model fan-out and owns the ring adapter."""

    def __init__(
        self,
        inference,
        cluster_manager,
        models_dir: Optional[str] = None,
        api_callback_addr: str = "",
        max_seq: int = 4096,
        param_dtype: str = "bfloat16",
        request_timeout_s: float = 600.0,
        weight_quant_bits: int = 0,
        ring_client_factory=None,
    ) -> None:
        self.inference = inference
        self.cluster = cluster_manager
        self.models_dir = models_dir
        self.api_callback_addr = api_callback_addr  # host:grpc_port for SendToken
        self.max_seq = max_seq
        self.param_dtype = param_dtype
        self.request_timeout_s = request_timeout_s
        self.weight_quant_bits = weight_quant_bits
        # injectable gRPC channel factory for the adapters this manager
        # builds (tests/fakes pattern: the whole manager runs over fakes)
        self._ring_client_factory = ring_client_factory
        # instance -> signature of the load body last successfully shipped
        # (dnet_tpu/membership/delta.py).  Entries survive re-solves —
        # including for quarantined shards — so a rejoin whose parameters
        # are unchanged rides the delta path too; the shard-side proof in
        # /update_topology (409 on mismatch) is the safety net for a shard
        # that restarted and silently lost its weights.
        self._last_load: dict = {}

    @property
    def current_model_id(self) -> Optional[str]:
        return self.inference.model_id

    def is_model_available(self, model_id: str) -> bool:
        return resolve_model_dir(model_id, self.models_dir) is not None

    async def load_model(
        self,
        model_id: str,
        max_seq: Optional[int] = None,
        delta: bool = False,
    ) -> float:
        """Fan the topology out to every shard.  With ``delta=True``
        (recovery/rejoin re-solves) shards whose load body is unchanged
        since their last successful load get a cheap ``/update_topology``
        (epoch bump + state drop + rewire, weights kept) instead of a full
        ``/load_model`` — recovery cost shrinks from full-cluster reload to
        the delta.  A delta update the shard refuses (409: restarted,
        different model/layers) falls back to the full load for that shard
        alone."""
        topo = self.cluster.current_topology
        if topo is None:
            raise RuntimeError("no topology; POST /v1/prepare_topology_manual first")
        model_dir = resolve_model_dir(model_id, self.models_dir)
        if model_dir is None:
            raise FileNotFoundError(f"model {model_id!r} not found locally")
        t0 = time.perf_counter()
        by_instance = {d.instance: d for d in topo.devices}
        max_seq = max_seq or self.max_seq
        # remember the resolved value: recovery/rejoin reloads call with
        # max_seq=None and MUST reproduce the operator's last choice — a
        # different max_seq_len would change every body (silently turning
        # the delta reload into a full one) and resize every shard's KV
        self.max_seq = max_seq
        lanes = self._lanes_for(topo, model_dir)
        spec = 0 if lanes > 1 else self._spec_lookahead_for(topo, model_dir, max_seq)
        prefix = self._prefix_for(topo)

        bodies: dict = {}
        for a in topo.assignments:
            nxt = by_instance.get(a.next_instance)
            dev = by_instance[a.instance]
            bodies[a.instance] = {
                "model_path": model_id,
                "layers": a.layers,
                # the ring is fully wired, tail included: the tail's
                # next IS the head, which carries k-round mid-frames
                # AND decode-grant continuations (final tokens still go
                # to the API callback)
                "next_node": {"host": nxt.host, "grpc_port": nxt.grpc_port},
                "window_size": a.window_size,
                "residency_size": a.residency_size,
                "kv_bits": topo.kv_bits,
                "max_seq_len": max_seq,
                "api_callback_address": f"grpc://{self.api_callback_addr}",
                "param_dtype": self.param_dtype,
                "weight_quant_bits": self.weight_quant_bits,
                # mesh-backed shards: the solve (or manual topology) may
                # give this ring node a host-local tp/sp mesh; 0 defers
                # to the shard's own DNET_SHARD_MESH_* defaults.  sp
                # must divide the LOAD-time max_seq (the solve checked
                # its own seq_len, which may differ) — drop it here
                # rather than failing every shard load.
                "mesh_tp": a.mesh_tp,
                "mesh_sp": self._check_sp(a, max_seq),
                # NamedSharding TP (parallel/tp.py): the solver's
                # mesh-slice placement pins pure-TP shards here; 1 keeps
                # a shard single-chip even when its DNET_TP says otherwise
                "tp_degree": a.tp_degree,
                # ring speculation: head drafts, tail verifies
                # (0 when the topology/model can't rewind — see
                # _spec_lookahead_for)
                "spec_lookahead": spec,
                # batched lanes: every shard allocates the same pooled
                # lane count so coalesced frames serve end to end
                "lanes": lanes,
                # ring prefix caching: same snapshot capacity on every
                # shard (the API index mirrors their LRU sequence)
                "prefix_cache": prefix,
                # membership epoch (dnet_tpu/membership/): the shard pins
                # it and fences frames/RPCs from any other epoch
                "epoch": topo.epoch,
                # hop codec: DNET_WIRE_CODEC=auto makes qsparse8 the
                # default for hops that actually CROSS hosts (~4x fewer
                # DCN bytes) while same-host/loopback hops — and every
                # single-shard "ring" — stay lossless, so greedy SSE
                # parity holds out of the box (transport/wire_pipeline.py)
                "wire_codec": self._hop_codec(dev, nxt, len(topo.assignments)),
            }
        if delta:
            changed, unchanged = split_delta(self._last_load, bodies)
        else:
            changed, unchanged = dict(bodies), {}

        async with httpx.AsyncClient(timeout=self.request_timeout_s) as client:

            async def ship(a) -> None:
                """One shard's load leg: cheap delta first where eligible,
                full /load_model otherwise."""
                dev = by_instance[a.instance]
                body = bodies[a.instance]
                if a.instance in unchanged:
                    if await self._update_topology(client, dev, body):
                        # stored signature already equals this body's (that
                        # is what `unchanged` means) — nothing to re-store
                        return
                    # the shard could not prove it still holds the
                    # weights (restart while quarantined, different
                    # model): full load for this shard alone
                    log.warning(
                        "delta update of %s refused; falling back to full "
                        "load", a.instance,
                    )
                url = f"http://{dev.host}:{dev.http_port}/load_model"
                r = await client.post(url, json=body)
                if r.status_code != 200:
                    # a half-shipped topology must not leave stale
                    # signatures claiming this shard is loadable by delta
                    self._last_load.pop(a.instance, None)
                    raise RuntimeError(
                        f"shard {a.instance} load failed ({r.status_code}): {r.text}"
                    )
                self._last_load[a.instance] = body_signature(body)

            # shards load concurrently: weight reads are the dominant cost
            # and are independent per shard, so wall time is the slowest
            # shard instead of the sum.  Every leg runs to completion
            # (return_exceptions) so one failed shard cannot strand its
            # peers' signature bookkeeping mid-flight; the first failure
            # then surfaces exactly like the old sequential loop's raise.
            outcomes = await asyncio.gather(
                *(ship(a) for a in topo.assignments), return_exceptions=True
            )
            for exc in outcomes:
                if isinstance(exc, BaseException):
                    raise exc

        # tokenizer API-side (reference model_manager.py:169-182)
        tokenizer = load_tokenizer(model_dir)

        head = by_instance[topo.head_instance()]
        from dnet_tpu.api.ring import RingApiAdapter
        from dnet_tpu.config import get_settings

        old = self.inference.adapter
        adapter = RingApiAdapter(
            head_addr=f"{head.host}:{head.grpc_port}",
            callback_url=f"grpc://{self.api_callback_addr}",
            shard_grpc_addrs=[
                f"{by_instance[a.instance].host}:{by_instance[a.instance].grpc_port}"
                for a in topo.assignments
            ],
            ring_client_factory=self._ring_client_factory,
            max_seq_len=max_seq,
            auto_steps=get_settings().api.ring_auto_steps,
            lanes=max(lanes, 1),
            prefix_cache=prefix,
            epoch=topo.epoch,
        )
        await adapter.start()
        self.inference.adapter = adapter
        # lane pools hold exactly `lanes` KV rows per shard: admission must
        # queue (not hard-fail) requests beyond that
        self.inference.set_concurrency_limit(lanes if lanes > 1 else None)
        self.inference.tokenizer = tokenizer
        self.inference.model_id = model_id
        if old is not None:
            await old.shutdown()
        dt = time.perf_counter() - t0
        log.info(
            "ring model %s loaded across %d shard(s) in %.1fs "
            "(epoch %d, %d full load(s), %d delta update(s))",
            model_id, len(topo.assignments), dt, topo.epoch,
            len(changed), len(unchanged),
        )
        return dt

    async def _update_topology(self, client, dev, body) -> bool:
        """One shard's cheap delta half: POST /update_topology.  True on
        success; False (any refusal or transport failure) sends the caller
        down the full-load path for that shard."""
        url = f"http://{dev.host}:{dev.http_port}/update_topology"
        try:
            r = await client.post(
                url,
                json={
                    "model_path": body["model_path"],
                    "layers": body["layers"],
                    "epoch": body["epoch"],
                    "next_node": body["next_node"],
                },
            )
        except httpx.HTTPError as exc:
            log.warning("update_topology on %s failed: %s", dev.instance, exc)
            return False
        if r.status_code != 200:
            log.warning(
                "update_topology on %s answered %d: %s",
                dev.instance, r.status_code, r.text,
            )
            return False
        return True

    @staticmethod
    def _single_round_resident(topo) -> bool:
        """The shared topology precondition for lanes / prefix caching /
        ring speculation: every assignment is one contiguous run (the
        prompt visits each shard once) with no streaming window (resident
        KV/weights)."""
        return not any(
            len(_contiguous_runs(a.layers)) > 1 or a.window_size > 0
            for a in topo.assignments
        )

    @staticmethod
    def _probe_model(model_dir):
        """(ModelConfig, ring model class) from a local checkpoint dir —
        THE config.json probe shared by every API-side model-capability
        gate (lanes, speculation)."""
        import json
        from pathlib import Path

        from dnet_tpu.models import ModelConfig, get_ring_model_cls

        cfg = ModelConfig.from_hf(
            json.loads((Path(model_dir) / "config.json").read_text())
        )
        return cfg, get_ring_model_cls(cfg.model_type)

    def _lanes_for(self, topo, model_dir) -> int:
        """Batched-lane preconditions the API can check up front: a
        configured lane count, a single-round resident topology, and a
        model with gated KV writes (LanePool hard-fails on
        supports_kv_commit=False — degrading to lanes=1 HERE keeps
        /load_model serving instead of bubbling that NotImplementedError).
        Mesh-backed shards COMPOSE with lanes (r5: shard_map(vmap) lane
        programs).  Shards re-check at load."""
        from dnet_tpu.config import get_settings

        lanes = get_settings().api.ring_lanes
        if lanes <= 1:
            return 0
        if not self._single_round_resident(topo):
            log.info("ring lanes off: k-round or streaming topology")
            return 0
        try:
            cfg, model_cls = self._probe_model(model_dir)
            if not model_cls.supports_kv_commit:
                log.warning(
                    "ring_lanes=%d requested but %s has no gated KV writes; "
                    "degrading to lanes=1",
                    lanes, cfg.model_type,
                )
                return 0
        except Exception as exc:
            # an unprobeable model must not wedge /load_model either way:
            # serve single-lane and say why
            log.warning(
                "ring lanes off (model probe failed: %s); serving lanes=1", exc
            )
            return 0
        return lanes

    def _prefix_for(self, topo) -> int:
        """Ring prefix-cache preconditions: a configured capacity and a
        single-round resident topology (a streamed shard keeps per-layer
        kv lists; a k-round prompt visits shards twice)."""
        from dnet_tpu.config import get_settings

        cap = get_settings().api.prefix_cache
        if cap <= 0:
            return 0
        if not self._single_round_resident(topo):
            log.info("ring prefix cache off: k-round or streaming topology")
            return 0
        return cap

    def _spec_lookahead_for(self, topo, model_dir, max_seq: int) -> int:
        """Ring speculation preconditions the API can check up front: a
        configured lookahead, a single-round non-streaming topology, and a
        rewind-safe cache layout.  Shards still re-check their own
        invariants at load."""
        from dnet_tpu.config import get_settings

        L = get_settings().api.spec_lookahead
        if L <= 0:
            return 0
        if not self._single_round_resident(topo):
            log.info("ring speculation off: k-round or streaming topology")
            return 0
        try:
            cfg, model_cls = self._probe_model(model_dir)
            model = model_cls(cfg, range(cfg.num_hidden_layers))
            if not model.kv_rewindable(max_seq):
                log.info(
                    "ring speculation off: %s cache cannot rewind",
                    cfg.model_type,
                )
                return 0
        except Exception as exc:
            log.warning("ring speculation off (model probe failed: %s)", exc)
            return 0
        return L

    _LOOPBACK_HOSTS = ("127.0.0.1", "::1", "localhost")

    @classmethod
    def _canonical_host(cls, host: str) -> str:
        """Best-effort canonical address for same-host comparison: a
        machine registered once by hostname and once by LAN IP must not
        be classified as two hosts (that would silently put the lossy
        codec on a hop that pays no DCN).  Resolution failures fall back
        to the raw name.  Load-time control plane only, never the serving
        path — and cached per host so repeated (delta) loads pay one
        resolver round trip per name, not one per hop per load."""
        if host in cls._LOOPBACK_HOSTS:
            return "127.0.0.1"
        return _resolve_host_cached(host)

    @classmethod
    def _hop_codec(cls, dev, nxt, n_shards: int) -> str:
        """Resolve this shard's hop codec (DNET_WIRE_CODEC).  ``auto``
        picks qsparse8_v1 (~7x byte reduction, BENCH_r03) only for hops
        that cross hosts — a same-host/loopback hop pays no DCN and keeps
        the exact lossless cast, and a single-shard ring has no hidden
        hops at all (its one "hop" is the tail->head continuation stream,
        token frames the codec never touches)."""
        from dnet_tpu.config import get_settings

        codec = get_settings().wire.codec
        if codec != "auto":
            return codec
        if n_shards <= 1 or nxt is None:
            return "lossless"
        same_host = dev.host == nxt.host or (
            cls._canonical_host(dev.host) == cls._canonical_host(nxt.host)
        )
        codec = "lossless" if same_host else "qsparse8"
        log.info(
            "hop codec %s -> %s: %s (%s)",
            dev.instance, nxt.instance, codec,
            "same host" if same_host else "crosses hosts",
        )
        return codec

    @staticmethod
    def _check_sp(a, max_seq: int) -> int:
        if a.mesh_sp > 1 and max_seq % a.mesh_sp != 0:
            log.warning(
                "%s: planned mesh_sp=%d does not divide max_seq_len=%d; "
                "serving without sequence parallelism on this node",
                a.instance, a.mesh_sp, max_seq,
            )
            return 1
        return a.mesh_sp

    async def unload_model(self) -> None:
        topo = self.cluster.current_topology
        self._last_load.clear()  # unloaded shards hold nothing to delta from
        self.inference.model_id = None
        self.inference.tokenizer = None
        adapter = self.inference.adapter
        if adapter is not None:
            await adapter.shutdown()
            self.inference.adapter = None
        if topo is None:
            return
        by_instance = {d.instance: d for d in topo.devices}
        async with httpx.AsyncClient(timeout=60.0) as client:

            async def drop(a) -> None:
                dev = by_instance[a.instance]
                try:
                    await client.post(f"http://{dev.host}:{dev.http_port}/unload_model")
                except httpx.HTTPError as exc:
                    log.warning("unload on %s failed: %s", a.instance, exc)

            # independent per-shard unloads: fan out, don't serialize
            await asyncio.gather(*(drop(a) for a in topo.assignments))
