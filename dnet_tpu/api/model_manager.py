"""Model lifecycle on the API node.

Single-process mode: builds a LocalEngine + tokenizer in an executor.
Ring mode (two-role split) extends this with per-shard /load_model fan-out
(reference: src/dnet/api/model_manager.py:54-255).

Model resolution is local-only (zero-egress environments are first-class):
a model id is either a filesystem path or a subdirectory of
`DNET_API_MODELS_DIR` (repo id slashes replaced by `--`, HF-cache style).
"""

from __future__ import annotations

import asyncio
import time
from pathlib import Path
from typing import Optional

from dnet_tpu.config import get_settings
from dnet_tpu.utils.logger import get_logger
from dnet_tpu.utils.tokenizer import load_tokenizer

log = get_logger()


def resolve_model_dir(model_id: str, models_dir: Optional[str | Path] = None) -> Optional[Path]:
    p = Path(model_id).expanduser()
    if p.is_dir() and (p / "config.json").is_file():
        return p
    if models_dir:
        base = Path(models_dir).expanduser()
        for cand in (
            base / model_id,
            base / model_id.replace("/", "--"),
            base / model_id.split("/")[-1],
        ):
            if cand.is_dir() and (cand / "config.json").is_file():
                return cand
    return None


class LocalModelManager:
    """Owns the engine + tokenizer for single-process serving."""

    def __init__(
        self,
        inference_manager,
        models_dir: Optional[str] = None,
        max_seq: int = 4096,
        param_dtype: str = "bfloat16",
        mesh: Optional[dict] = None,  # {"pp","tp","dp","sp"} -> MeshEngine
        weight_quant_bits: int = 0,
        weight_quant_group: int = 0,
        kv_bits: int = 0,
        batch_slots: int = 1,
        prefix_cache: int = 0,
        spec_lookahead: int = 0,
    ) -> None:
        self.inference = inference_manager
        self.models_dir = models_dir
        self.max_seq = max_seq
        self.param_dtype = param_dtype
        self.weight_quant_bits = weight_quant_bits
        self.weight_quant_group = weight_quant_group
        self.kv_bits = kv_bits
        self.batch_slots = batch_slots
        self.prefix_cache = prefix_cache
        self.spec_lookahead = spec_lookahead
        # active when any axis is parallel or pp is left to infer (pp=0 with
        # another axis set, or an explicit pp)
        self.mesh = mesh if mesh and (any(v > 1 for v in mesh.values()) or mesh.get("pp", 0) > 1) else None
        self.engine = None
        self.model_dir: Optional[Path] = None

    @property
    def current_model_id(self) -> Optional[str]:
        return self.inference.model_id

    def is_model_available(self, model_id: str) -> bool:
        from dnet_tpu.api.catalog import split_variant

        return resolve_model_dir(split_variant(model_id)[0], self.models_dir) is not None

    async def load_model(self, model_id: str, max_seq: Optional[int] = None) -> float:
        """Returns load time in seconds; raises on failure.

        `<id>:int8` / `<id>:int4` quant-variant aliases (catalog rows the
        reference enumerates per model, src/dnet/api/catalog.py:4-175) load
        the BASE checkpoint with weight-only quantization overridden."""
        from dnet_tpu.api.catalog import split_variant

        base_id, variant_bits = split_variant(model_id)
        model_dir = resolve_model_dir(base_id, self.models_dir)
        if model_dir is None:
            raise FileNotFoundError(
                f"model {model_id!r} not found locally (models_dir={self.models_dir})"
            )
        wq_bits = self.weight_quant_bits if variant_bits is None else variant_bits
        wq_group = self.weight_quant_group
        if variant_bits:
            from dnet_tpu.ops.quant import DEFAULT_GROUP, DEFAULT_GROUP_Q4

            wq_group = wq_group or (
                DEFAULT_GROUP_Q4 if variant_bits == 4 else DEFAULT_GROUP
            )
        t0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        from dnet_tpu.sched import sched_enabled

        # DNET_SCHED=1: the iteration-level scheduler (dnet_tpu/sched/)
        # becomes the local serving engine — it needs the batched chunked-
        # prefill surface, so a single-sequence load is widened to a
        # BatchedEngine with the scheduler's slot count
        sched_on = sched_enabled() and self.mesh is None
        batch_slots = self.batch_slots
        if sched_on:
            sched_cfg = get_settings().sched
            batch_slots = sched_cfg.sched_slots or max(self.batch_slots, 8)

        def _build():
            from dnet_tpu.core.kvcache import resolve_kv_bits

            kv_dtype, kv_quant_bits = resolve_kv_bits(self.kv_bits)
            if self.mesh is not None:
                dp, sp = self.mesh.get("dp", 1), self.mesh.get("sp", 1)
                # sp rides inside the rotation program (sharded KV) and dp
                # shards slots over lanes (r4) — all four axes compose
                use_pipelined = (
                    self.batch_slots > 1 and self.batch_slots % dp == 0
                )
                if use_pipelined:
                    # pre-check pipelined preconditions so an incompatible
                    # config degrades to the sequential mesh instead of
                    # failing load_model
                    import jax as _jax

                    from dnet_tpu.models import (
                        ModelConfig as _MC,
                        get_ring_model_cls as _cls,
                    )
                    from dnet_tpu.utils.checkpoint import Checkpoint as _Ck

                    _cfg = _MC.from_hf(_Ck(model_dir).config)
                    _tp = self.mesh.get("tp", 1)
                    _pp = self.mesh.get("pp", 0)
                    if _pp <= 0:
                        from dnet_tpu.parallel.pipelined import resolve_pp

                        _pp = resolve_pp(
                            len(_jax.devices()), _tp * dp,
                            self.mesh.get("sp", 1), _cfg.num_hidden_layers,
                        )
                    _mcls = _cls(_cfg.model_type)
                    _inst = _mcls(_cfg, range(_cfg.num_hidden_layers))
                    if not _mcls.supports_kv_commit:
                        log.warning(
                            "pipelined batching unsupported for %s; serving "
                            "sequential mesh",
                            _cfg.model_type,
                        )
                        use_pipelined = False
                    elif self.batch_slots // dp < _pp:
                        log.warning(
                            "batch_slots=%d gives %d slots per dp lane, < "
                            "pp=%d: cannot fill the pipeline; serving "
                            "sequential mesh (raise batch_slots)",
                            self.batch_slots, self.batch_slots // dp, _pp,
                        )
                        use_pipelined = False
                if use_pipelined:
                    if self.spec_lookahead:
                        log.warning(
                            "DNET_API_SPEC_LOOKAHEAD is not supported by the "
                            "pipelined mesh engine (per-slot acceptance "
                            "lengths diverge); disabled"
                        )
                    # staggered-microbatch pipeline: batch_slots concurrent
                    # sequences keep every pp rank busy every stage-step
                    from dnet_tpu.parallel.pipelined import PipelinedMeshEngine

                    engine = PipelinedMeshEngine(
                        model_dir,
                        pp=self.mesh.get("pp", 0),
                        tp=self.mesh.get("tp", 1),
                        sp=self.mesh.get("sp", 1),
                        dp=dp,
                        slots=self.batch_slots,
                        max_seq=max_seq or self.max_seq,
                        param_dtype=self.param_dtype,
                        kv_dtype=kv_dtype,
                        kv_quant_bits=kv_quant_bits,
                        weight_quant_bits=wq_bits,
                        quant_group=wq_group,
                        prefix_cache_size=self.prefix_cache,
                    )
                    return engine, load_tokenizer(model_dir)
                if self.batch_slots > 1 and self.batch_slots % dp != 0:
                    log.warning(
                        "batch_slots=%d not divisible by dp=%d; pipelined "
                        "batching needs whole lanes — serving sequential mesh",
                        self.batch_slots, dp,
                    )
                from dnet_tpu.parallel.engine import MeshEngine

                engine = MeshEngine(
                    model_dir,
                    pp=self.mesh.get("pp", 0),
                    tp=self.mesh.get("tp", 1),
                    dp=self.mesh.get("dp", 1),
                    sp=self.mesh.get("sp", 1),
                    max_seq=max_seq or self.max_seq,
                    param_dtype=self.param_dtype,
                    kv_dtype=kv_dtype,
                    kv_quant_bits=kv_quant_bits,
                    weight_quant_bits=wq_bits,
                    quant_group=wq_group,
                    prefix_cache_size=self.prefix_cache,
                    spec_lookahead=self.spec_lookahead,
                )
                # the mesh chunk programs (K-step full-ring scans) are the
                # most expensive compiles in the codebase: do them now, not
                # mid-stream on the first request's ramp
                if get_settings().api.warm_on_load:
                    engine.warm_chunks()
            elif batch_slots > 1:
                from dnet_tpu.core.batch import BatchedEngine

                # per-lane acceptance (r4): greedy lanes speculate and
                # advance unevenly; sampled lanes take the plain batched step
                engine = BatchedEngine(
                    model_dir,
                    slots=batch_slots,
                    max_seq=max_seq or self.max_seq,
                    param_dtype=self.param_dtype,
                    kv_dtype=kv_dtype,
                    kv_quant_bits=kv_quant_bits,
                    weight_quant_bits=wq_bits,
                    weight_quant_group=wq_group,
                    prefix_cache_size=self.prefix_cache,
                    spec_lookahead=self.spec_lookahead,
                )
                # compile the batched step + fused-chunk widths now, not on
                # the first request while every lane shares one executor
                if get_settings().api.warm_on_load:
                    engine.warm_chunks()
            else:
                from dnet_tpu.core.engine import LocalEngine

                # draft-MODEL speculation: local-engine single-sequence
                # serving only (batched/mesh engines draft by prompt-lookup)
                draft_dir = None
                draft_id = get_settings().api.draft_model
                if draft_id and self.spec_lookahead > 0:
                    draft_dir = resolve_model_dir(draft_id, self.models_dir)
                    if draft_dir is None:
                        log.warning(
                            "DNET_API_DRAFT_MODEL=%s not found; drafting by "
                            "prompt-lookup instead", draft_id,
                        )
                engine = LocalEngine(
                    model_dir,
                    max_seq=max_seq or self.max_seq,
                    param_dtype=self.param_dtype,
                    kv_dtype=kv_dtype,
                    kv_quant_bits=kv_quant_bits,
                    weight_quant_bits=wq_bits,
                    weight_quant_group=wq_group,
                    prefix_cache_size=self.prefix_cache,
                    spec_lookahead=self.spec_lookahead,
                    draft_dir=draft_dir,
                )
                # compile the chunked decode widths now, not mid-stream on
                # the first request's ramp
                if get_settings().api.warm_on_load:
                    engine.warm_chunks()
            return engine, load_tokenizer(model_dir)

        engine, tokenizer = await loop.run_in_executor(None, _build)

        # swap adapter engine atomically
        old_adapter = self.inference.adapter
        from dnet_tpu.api.strategies import BatchedLocalAdapter, LocalAdapter
        from dnet_tpu.core.batch import BatchedEngine
        from dnet_tpu.parallel.pipelined import PipelinedMeshEngine

        adapter = None
        if sched_enabled():
            if isinstance(engine, BatchedEngine):
                from dnet_tpu.sched import SchedulerAdapter

                adapter = SchedulerAdapter(engine)
            else:
                log.warning(
                    "DNET_SCHED=1: %s lacks the chunked-prefill batched "
                    "surface; serving the legacy adapter",
                    type(engine).__name__,
                )
        if adapter is None:
            adapter = (
                BatchedLocalAdapter(engine)
                if isinstance(engine, (BatchedEngine, PipelinedMeshEngine))
                else LocalAdapter(engine)
            )
        await adapter.start()
        self.inference.adapter = adapter
        self.inference.tokenizer = tokenizer
        self.inference.model_id = model_id
        self.engine = engine
        self.model_dir = model_dir
        if old_adapter is not None:
            await old_adapter.shutdown()
        dt = time.perf_counter() - t0
        log.info("loaded model %s from %s in %.1fs", model_id, model_dir, dt)
        return dt

    async def unload_model(self) -> None:
        self.inference.model_id = None
        self.inference.tokenizer = None
        adapter = self.inference.adapter
        if adapter is not None:
            await adapter.shutdown()
        self.engine = None
        self.model_dir = None
        import gc

        gc.collect()
