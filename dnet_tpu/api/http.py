"""API-node HTTP server (aiohttp): OpenAI-compatible /v1 routes.

Routes (reference: src/dnet/api/http_api.py:75-93):
  POST /v1/chat/completions    — SSE streaming + aggregate
  GET  /v1/models              — catalog + currently loaded model
  POST /v1/load_model          — load (single-process or fan-out)
  POST /v1/unload_model
  GET  /v1/topology            — current topology (ring mode)
  GET  /v1/devices             — discovered devices
  GET  /health                 — + rolling SLO status (degraded when burning)
  GET  /metrics                — Prometheus text exposition (dnet_tpu.obs)
  GET  /v1/cluster/metrics     — every node's /metrics federated (node labels)
  GET  /v1/debug/timeline/{rid} — one request's flight-recorder spans;
                                  ?cluster=1 stitches every shard's spans
                                  into one skew-corrected timeline; the
                                  response embeds the request's
                                  critical-path segment ledger
  GET  /v1/debug/sched          — scheduler tick flight-recorder ring
                                  (sched/flight.py; DNET_SCHED mode)
  GET  /v1/debug/trace/{rid}    — one request as Chrome trace-event /
                                  Perfetto JSON (?cluster=1 stitches)
  GET  /v1/debug/trace?last_s=N — serving-window Perfetto dump (every
                                  retained timeline + tick records +
                                  wide-event instants)
  GET  /v1/debug/events         — structured wide-event ring
                                  (obs/events.py); ?rid= / ?name= /
                                  ?last_s= filter, ?cluster=1 merges every
                                  shard's ring onto this node's clock
FastAPI is not available in this image; aiohttp's request handling + a thin
pydantic validation shim cover the same surface.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from aiohttp import web
from pydantic import ValidationError

from dnet_tpu.admission.controller import AdmissionRejected
from dnet_tpu.api.catalog import model_catalog
from dnet_tpu.api.inference import (
    BackpressureError,
    DeadlineExceededError,
    EngineCapabilityError,
    InferenceError,
    InferenceManager,
    PromptTooLongError,
    ServiceDegradedError,
)
from dnet_tpu.api.schemas import (
    ChatCompletionRequest,
    HealthResponse,
    LoadModelRequest,
    LoadModelResponse,
    ModelInfo,
    ModelList,
    UnloadModelResponse,
)
from dnet_tpu.utils.logger import get_logger

log = get_logger()


def _json_error(
    status: int,
    message: str,
    err_type: str = "invalid_request_error",
    retry_after_s: Optional[float] = None,
):
    headers = None
    if retry_after_s is not None:
        # Retry-After is integral seconds per RFC 9110; never advertise 0
        headers = {"Retry-After": str(max(1, round(retry_after_s)))}
    return web.json_response(
        {"error": {"message": message, "type": err_type}},
        status=status,
        headers=headers,
    )


class ApiHTTPServer:
    def __init__(
        self,
        inference: InferenceManager,
        model_manager,
        cluster_manager=None,
        fleet=None,
    ) -> None:
        self.inference = inference
        self.model_manager = model_manager
        self.cluster_manager = cluster_manager
        # DNET_FLEET>1: a FleetManager routes decode endpoints across
        # replicas; None (the default) keeps the single-ring path with
        # zero new code between request and stream
        self.fleet = fleet
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.router.add_post("/v1/chat/completions", self.chat_completions)
        self.app.router.add_post("/v1/completions", self.completions)
        self.app.router.add_post("/v1/embeddings", self.embeddings)
        self.app.router.add_get("/v1/models", self.list_models)
        self.app.router.add_post("/v1/load_model", self.load_model)
        self.app.router.add_post("/v1/unload_model", self.unload_model)
        self.app.router.add_post("/v1/prepare_topology", self.prepare_topology)
        self.app.router.add_post("/v1/prepare_topology_manual", self.prepare_topology_manual)
        self.app.router.add_get("/v1/topology", self.get_topology)
        self.app.router.add_post("/v1/calibrate", self.calibrate)
        self.app.router.add_get("/v1/devices", self.get_devices)
        self.app.router.add_get("/health", self.health)
        self.app.router.add_get("/metrics", self.metrics)
        self.app.router.add_get("/v1/cluster/metrics", self.cluster_metrics)
        self.app.router.add_get(
            "/v1/debug/timeline/{rid}", self.debug_timeline
        )
        self.app.router.add_get("/v1/debug/sched", self.debug_sched)
        self.app.router.add_get("/v1/debug/trace", self.debug_trace_window)
        self.app.router.add_get("/v1/debug/trace/{rid}", self.debug_trace)
        self.app.router.add_get("/v1/debug/events", self.debug_events)
        self.app.router.add_get("/v1/debug/fleet", self.debug_fleet)
        self._runner: Optional[web.AppRunner] = None
        # peers seen by earlier /v1/cluster/metrics scrapes: a peer that
        # leaves discovery must drop to scrape_ok 0, not freeze at 1
        self._scraped_peers: set = set()

    # ---- lifecycle ----------------------------------------------------
    async def start(self, host: str, port: int) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        log.info("API HTTP listening on %s:%d", host, port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
            self._runner = None

    # ---- decode-endpoint scaffolding ---------------------------------
    def _gate(self):
        """Shared pre-admission checks for decode endpoints (None = pass)."""
        if self.fleet is not None:
            # fleet mode: any serving replica admits the request — the
            # router walks the candidates; only a fleet with NO serving
            # replica falls through to the single-ring diagnostics below
            # (which then describe the primary honestly)
            if any(
                h.serving and getattr(h.inference, "ready", False)
                for h in self.fleet.handles()
            ):
                return None
        admission = self.inference.admission
        if admission.draining:
            # drain window (SIGTERM): in-flight streams finish; new work
            # is told exactly when to come back
            return _json_error(
                503,
                "server is draining for shutdown",
                "service_unavailable",
                retry_after_s=admission.retry_after_s(),
            )
        if not self.inference.ready:
            return _json_error(400, "no model loaded; POST /v1/load_model first")
        monitor = self.inference.failure_monitor
        if monitor is not None and monitor.degraded:
            return _json_error(
                503,
                f"ring degraded: shard(s) {monitor.down_shards()} down",
                "service_unavailable",
            )
        return None

    async def _sse(self, request, req, reshape) -> web.StreamResponse:
        """Stream the decode chunks as SSE; `reshape(chunk) -> [json str]`.

        The FIRST chunk is awaited before the SSE response commits to a
        200: anything shed before the first token — admission rejection
        (429 + Retry-After), drain (503), expired deadline (504), prompt
        too long (400), prefill backpressure (429) — keeps its real HTTP
        status instead of dying inside a 200 stream.  Past the first
        chunk the status is sent; errors become in-band SSE events.

        The generator is ALWAYS closed on the way out: a client that
        disconnects mid-stream closes it (GeneratorExit), which fans
        cancel + reset_cache out through the ring (InferenceManager) and
        frees the admission slot immediately."""
        route_info: dict = {}
        if self.fleet is not None:
            gen = self.fleet.stream(req, route_info)
        else:
            gen = self.inference.generate_stream(req)
        try:
            try:
                first = await gen.__anext__()
            except StopAsyncIteration:
                first = None
            except Exception as exc:
                return self._map_inference_errors(exc)
            headers = {
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            }
            if route_info.get("replica"):
                # per-replica outcome attribution for loadgen/report: the
                # serving replica is decided by first-chunk time (fleet
                # routing fills route_info during admission)
                headers["x-dnet-replica"] = route_info["replica"]
            resp = web.StreamResponse(status=200, headers=headers)
            await resp.prepare(request)

            async def write_chunk(chunk) -> None:
                # serialize + flush, timed as the request's sse_flush
                # segment (obs/critical_path.py): the one leg of a
                # request's story that happens after the driver hands a
                # chunk back
                from dnet_tpu.obs import get_recorder

                t_w = time.perf_counter()
                for payload in reshape(chunk):
                    await resp.write(f"data: {payload}\n\n".encode())
                get_recorder().span(
                    chunk.id, "sse_flush",
                    (time.perf_counter() - t_w) * 1000.0,
                )

            try:
                if first is not None:
                    await write_chunk(first)
                    async for chunk in gen:
                        await write_chunk(chunk)
                await resp.write(b"data: [DONE]\n\n")
            except PromptTooLongError as exc:
                err = json.dumps(
                    {"error": {"message": str(exc), "type": "invalid_request_error"}}
                )
                await resp.write(f"data: {err}\n\n".encode())
            except DeadlineExceededError as exc:
                err = json.dumps(
                    {"error": {"message": str(exc), "type": "deadline_exceeded"}}
                )
                await resp.write(f"data: {err}\n\n".encode())
            except BackpressureError as exc:
                # capacity shed mid-stream is not a server fault: keep the
                # status contract's semantics in the in-band event type
                err = json.dumps(
                    {"error": {"message": str(exc), "type": "rate_limit_exceeded"}}
                )
                await resp.write(f"data: {err}\n\n".encode())
            except InferenceError as exc:
                err = json.dumps({"error": {"message": str(exc), "type": "server_error"}})
                await resp.write(f"data: {err}\n\n".encode())
            except ConnectionResetError:
                log.info("client disconnected mid-stream")
            await resp.write_eof()
            return resp
        finally:
            # closing an already-finished generator is a no-op; closing an
            # abandoned one (disconnect / handler error) triggers the
            # cancel fan-out in InferenceManager._run
            await gen.aclose()

    def _map_inference_errors(self, exc: Exception):
        from dnet_tpu.fleet.router import FleetSheddingError

        if isinstance(exc, FleetSheddingError):
            # every fleet replica shed: same client contract as a single
            # ring's capacity shed — 429 with the soonest honest Retry-After
            return _json_error(
                429,
                str(exc),
                "rate_limit_exceeded",
                retry_after_s=exc.retry_after_s,
            )
        if isinstance(exc, AdmissionRejected):
            status = 503 if exc.reason == "draining" else 429
            return _json_error(
                status,
                str(exc),
                "service_unavailable" if status == 503 else "rate_limit_exceeded",
                retry_after_s=exc.retry_after_s,
            )
        if isinstance(exc, BackpressureError):
            return _json_error(
                429,
                str(exc),
                "rate_limit_exceeded",
                retry_after_s=self.inference.admission.retry_after_s(),
            )
        if isinstance(exc, DeadlineExceededError):
            return _json_error(504, str(exc), "deadline_exceeded")
        if isinstance(exc, PromptTooLongError):
            return _json_error(400, str(exc))
        if isinstance(exc, EngineCapabilityError):
            # the serving config asked this engine for something it cannot
            # do — a 4xx the operator fixes, not a server fault
            return _json_error(422, str(exc), "invalid_request_error")
        if isinstance(exc, ServiceDegradedError):
            return _json_error(503, str(exc), "service_unavailable")
        if isinstance(exc, ConnectionError):
            # transport-class failure before any chunk was written (a
            # broken channel, or an injected chaos fault at a pre-stream
            # point like `admit`): the request never started, so it is
            # retryable service unavailability — never a 500.  The chaos
            # campaign's status-code contract pins this.
            return _json_error(503, str(exc), "service_unavailable")
        if isinstance(exc, InferenceError):
            return _json_error(500, str(exc), "server_error")
        raise exc

    # ---- handlers -----------------------------------------------------
    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            req = ChatCompletionRequest.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            return _json_error(400, f"invalid request: {exc}")
        gate = self._gate()
        if gate is not None:
            return gate

        if req.stream:
            return await self._sse(
                request, req, lambda c: [c.model_dump_json(exclude_none=True)]
            )
        route_info: dict = {}
        try:
            if self.fleet is not None:
                result = await self.fleet.generate(req, route_info)
            else:
                result = await self.inference.generate(req)
        except Exception as exc:
            return self._map_inference_errors(exc)
        headers = (
            {"x-dnet-replica": route_info["replica"]}
            if route_info.get("replica")
            else None
        )
        return web.json_response(
            result.model_dump(exclude_none=True), headers=headers
        )

    async def completions(self, request: web.Request) -> web.StreamResponse:
        """Legacy /v1/completions: raw prompt, text_completion objects."""
        from dnet_tpu.api.inference import completion_logprobs
        from dnet_tpu.api.schemas import CompletionRequest

        try:
            req = CompletionRequest.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            return _json_error(400, f"invalid request: {exc}")
        gate = self._gate()
        if gate is not None:
            return gate

        if req.stream:
            state = {"first": True, "offset": len(req.prompt_text()) if req.echo else 0}

            def reshape(chunk):
                """Chat-style deltas -> completion chunks (echo emits the
                prompt before the first delta; logprobs use the completions
                shape)."""
                out = {
                    "id": chunk.id.replace("chatcmpl", "cmpl"),
                    "object": "text_completion",
                    "model": req.model,
                    "choices": [],
                }
                for c in chunk.choices:
                    text = c.delta.content or ""
                    if state["first"] and (text or c.finish_reason):
                        state["first"] = False
                        if req.echo:
                            text = req.prompt_text() + text
                    choice = {"index": 0, "text": text, "finish_reason": c.finish_reason}
                    if c.logprobs is not None:
                        lp = completion_logprobs(c.logprobs.content, state["offset"])
                        state["offset"] += sum(len(t) for t in lp.tokens)
                        choice["logprobs"] = lp.model_dump()
                    out["choices"].append(choice)
                if chunk.usage:
                    out["usage"] = chunk.usage.model_dump()
                return [json.dumps(out)]

            return await self._sse(request, req, reshape)
        route_info: dict = {}
        try:
            if self.fleet is not None:
                result = await self.fleet.generate(
                    req, route_info, method="generate_completion"
                )
            else:
                result = await self.inference.generate_completion(req)
        except Exception as exc:
            return self._map_inference_errors(exc)
        headers = (
            {"x-dnet-replica": route_info["replica"]}
            if route_info.get("replica")
            else None
        )
        return web.json_response(
            result.model_dump(exclude_none=True), headers=headers
        )

    async def embeddings(self, request: web.Request) -> web.Response:
        """Mean-pooled final-hidden-state embeddings (BEYOND the reference,
        whose embeddings schema exists in api/models.py with no serving
        path).  Local/batched/mesh strategies serve; the gRPC ring —
        where shards never ship hidden states to the API node — answers
        501."""
        from dnet_tpu.api.schemas import EmbeddingsRequest

        try:
            req = EmbeddingsRequest.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            return _json_error(400, f"invalid request: {exc}")
        gate = self._gate()
        if gate is not None:
            return gate
        try:
            result = await self.inference.embeddings(req)
        except NotImplementedError as exc:
            return _json_error(501, str(exc), "not_implemented")
        except ValueError as exc:
            return _json_error(400, str(exc))
        except Exception as exc:
            return self._map_inference_errors(exc)
        return web.json_response(result.model_dump())

    async def list_models(self, request: web.Request) -> web.Response:
        # quant-variant aliases listed alongside base ids (reference-style
        # per-variant catalog rows; `<id>:int8` resolves via resolve_variant)
        from dnet_tpu.api.catalog import expanded_catalog

        data = [ModelInfo(id=e.id) for e in expanded_catalog()]
        loaded = self.model_manager.current_model_id
        if loaded and all(m.id != loaded for m in data):
            data.append(ModelInfo(id=loaded))
        return web.json_response(ModelList(data=data).model_dump())

    async def load_model(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            req = LoadModelRequest.model_validate(body)
        except (json.JSONDecodeError, ValidationError) as exc:
            return _json_error(400, f"invalid request: {exc}")
        kwargs = {}
        if req.delta:
            # `delta` only reaches managers that speak it (the ring
            # manager); the single-process manager has no fan-out to diff
            import inspect

            params = inspect.signature(
                self.model_manager.load_model
            ).parameters
            if "delta" not in params:
                return _json_error(
                    400, "delta reload is only available in ring mode"
                )
            kwargs["delta"] = True
        try:
            dt = await self.model_manager.load_model(
                req.model, max_seq=req.max_seq_len, **kwargs
            )
        except FileNotFoundError as exc:
            return _json_error(404, str(exc), "model_not_found")
        except EngineCapabilityError as exc:
            # e.g. continuous batching requested over streamed weights or a
            # model without gated KV writes (core/batch.py): the config is
            # at fault, not the server — 422, with nothing half-loaded
            return _json_error(422, str(exc), "invalid_request_error")
        except Exception as exc:
            log.exception("load_model failed")
            return _json_error(500, f"load failed: {exc}", "server_error")
        return web.json_response(
            LoadModelResponse(model=req.model, load_time_s=dt).model_dump()
        )

    async def unload_model(self, request: web.Request) -> web.Response:
        await self.model_manager.unload_model()
        return web.json_response(UnloadModelResponse(message="unloaded").model_dump())

    async def prepare_topology(self, request: web.Request) -> web.Response:
        """Auto pipeline: discover -> profile -> solve (reference
        http_api.py:254-303)."""
        from dnet_tpu.api.schemas import PrepareTopologyRequest

        if self.cluster_manager is None:
            return _json_error(400, "not in ring mode (no discovery configured)")
        try:
            req = PrepareTopologyRequest.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            return _json_error(400, f"invalid request: {exc}")

        from dnet_tpu.api.model_manager import resolve_model_dir
        from dnet_tpu.parallel.solver import (
            model_profile_from_checkpoint,
            solve_topology,
        )

        model_dir = resolve_model_dir(
            req.model, getattr(self.model_manager, "models_dir", None)
        )
        if model_dir is None:
            return _json_error(404, f"model {req.model!r} not found locally", "model_not_found")

        devices = await self.cluster_manager.profile_cluster()
        if not devices:
            return _json_error(503, "no healthy shards discovered", "no_devices")
        # fold in measured stage-time ratios from earlier /v1/calibrate runs
        devices = self.cluster_manager.apply_stage_ratios(devices)
        try:
            profile = model_profile_from_checkpoint(
                model_dir,
                seq_len=req.seq_len,
                kv_bits=req.kv_bits,
                weight_quant_bits=getattr(
                    self.model_manager, "weight_quant_bits", 0
                ),
            )
            from dnet_tpu.config import get_settings

            topo = solve_topology(
                devices,
                profile,
                kv_bits=req.kv_bits,
                solver=get_settings().topology.solver,
                mip_gap=get_settings().topology.mip_gap,
            )
        except ValueError as exc:
            return _json_error(400, str(exc))
        topo.model = req.model
        # install (not assign): minting the membership epoch here is what
        # arms the zombie fence for the upcoming load fan-out
        self.cluster_manager.install_topology(topo)
        return web.json_response(
            {
                "status": "ok",
                "topology": {
                    "model": topo.model,
                    "num_layers": topo.num_layers,
                    "epoch": topo.epoch,
                    "solution": topo.solution,
                    "assignments": [
                        {
                            "instance": a.instance,
                            "layers": a.layers,
                            "next_instance": a.next_instance,
                            "window_size": a.window_size,
                            "residency_size": a.residency_size,
                            "mesh_tp": a.mesh_tp,
                            "mesh_sp": a.mesh_sp,
                        }
                        for a in topo.assignments
                    ],
                },
            }
        )

    async def prepare_topology_manual(self, request: web.Request) -> web.Response:
        """Manual layer assignment -> ring topology (reference
        http_api.py:305-403).  Requires ring mode (a cluster manager)."""
        from dnet_tpu.api.schemas import PrepareTopologyManualRequest

        if self.cluster_manager is None:
            return _json_error(400, "not in ring mode (no discovery configured)")
        try:
            req = PrepareTopologyManualRequest.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            return _json_error(400, f"invalid request: {exc}")

        from dnet_tpu.api.model_manager import resolve_model_dir
        from dnet_tpu.api.ring_manager import build_manual_topology

        model_dir = resolve_model_dir(
            req.model, getattr(self.model_manager, "models_dir", None)
        )
        if model_dir is None:
            return _json_error(404, f"model {req.model!r} not found locally", "model_not_found")
        num_layers = json.loads((model_dir / "config.json").read_text())[
            "num_hidden_layers"
        ]
        devices = await self.cluster_manager.healthy_devices()
        try:
            topo = build_manual_topology(
                req.model,
                num_layers,
                [a.model_dump() for a in req.assignments],
                devices,
                kv_bits=req.kv_bits,
            )
        except ValueError as exc:
            return _json_error(400, str(exc))
        self.cluster_manager.install_topology(topo)
        return web.json_response(
            {
                "status": "ok",
                "topology": {
                    "model": topo.model,
                    "num_layers": topo.num_layers,
                    "epoch": topo.epoch,
                    "assignments": [
                        {
                            "instance": a.instance,
                            "layers": a.layers,
                            "next_instance": a.next_instance,
                            "mesh_tp": a.mesh_tp,
                            "mesh_sp": a.mesh_sp,
                        }
                        for a in topo.assignments
                    ],
                },
            }
        )

    async def calibrate(self, request: web.Request) -> web.Response:
        """Probe every loaded shard's measured stage time, compare with the
        solver's predictions, optionally store the ratios for future solves
        (body: {"steps": 3, "apply": false})."""
        if self.cluster_manager is None:
            return _json_error(400, "not in ring mode (no discovery configured)")
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            body = {}
        if not isinstance(body, dict):
            return _json_error(400, "body must be a JSON object")
        try:
            steps = int(body.get("steps", 3) or 3)
        except (TypeError, ValueError):
            return _json_error(400, "steps must be an integer")
        if not 1 <= steps <= 16:
            return _json_error(400, "steps must be between 1 and 16")
        try:
            cals = await self.cluster_manager.calibrate_topology(steps=steps)
        except ValueError as exc:
            return _json_error(409, str(exc))
        if body.get("apply"):
            self.cluster_manager.store_stage_ratios(cals)
        from dnet_tpu.parallel.calibrate import max_rel_err

        return web.json_response(
            {
                "calibrations": [c.as_dict() for c in cals],
                "max_rel_err": max_rel_err(cals),
                "applied": bool(body.get("apply")),
            }
        )

    async def get_topology(self, request: web.Request) -> web.Response:
        if self.cluster_manager is None or getattr(self.cluster_manager, "current_topology", None) is None:
            return web.json_response({"topology": None})
        topo = self.cluster_manager.current_topology
        return web.json_response(
            {
                "topology": {
                    "model": topo.model,
                    "num_layers": topo.num_layers,
                    "kv_bits": topo.kv_bits,
                    "epoch": topo.epoch,
                    "assignments": [
                        {
                            "instance": a.instance,
                            "layers": a.layers,
                            "next_instance": a.next_instance,
                            "window_size": a.window_size,
                            "residency_size": a.residency_size,
                            "mesh_tp": a.mesh_tp,
                            "mesh_sp": a.mesh_sp,
                        }
                        for a in topo.assignments
                    ],
                    "solution": topo.solution,
                }
            }
        )

    async def get_devices(self, request: web.Request) -> web.Response:
        if self.cluster_manager is None:
            return web.json_response({"devices": []})
        devices = await self.cluster_manager.scan_devices()
        return web.json_response(
            {
                "devices": [
                    {
                        "instance": d.instance,
                        "host": d.host,
                        "http_port": d.http_port,
                        "grpc_port": d.grpc_port,
                        "is_manager": d.is_manager,
                        "slice_id": d.slice_id,
                        "chip_count": d.chip_count,
                    }
                    for d in devices
                ]
            }
        )

    async def health(self, request: web.Request) -> web.Response:
        from dnet_tpu.obs import get_slo_tracker
        from dnet_tpu.resilience.chaos import armed_summary

        body = HealthResponse(model=self.model_manager.current_model_id).model_dump()
        # armed chaos is ALWAYS visible here: an operator reading /health
        # during an incident must be able to tell injected faults from
        # real ones at a glance (absent when no chaos is armed)
        chaos = armed_summary()
        if chaos is not None:
            body["chaos"] = chaos
        # membership view: the installed topology's epoch and the fenced-out
        # (quarantined, still-probed) shards — a degraded-membership ring is
        # visible here and through the federation scrape at a glance
        if self.cluster_manager is not None:
            body["epoch"] = getattr(self.cluster_manager, "epoch", 0)
        monitor = self.inference.failure_monitor
        quarantine = getattr(monitor, "quarantine", None)
        if quarantine is not None:
            # quarantined shards don't degrade `status` — the re-solved
            # ring serves fine, just below full capacity — but operators
            # (and the rejoin runbook) see exactly who is out and for how
            # long they've probed green
            body["quarantine"] = quarantine.snapshot()
        if monitor is not None and monitor.health:
            body["shards"] = monitor.snapshot()
            if monitor.degraded:
                body["status"] = "degraded"
        # rolling SLO windows (obs/slo.py): a burning SLO degrades /health
        # even while every shard is up — slow is its own kind of down
        slo = get_slo_tracker().snapshot()
        body["slo"] = slo
        if slo["burning"]:
            body["status"] = "degraded"
        # admission picture: queue/in-flight depths, and the drain state —
        # "draining" wins over "degraded" (load balancers must stop
        # routing here regardless of how healthy the ring looks)
        admission = self.inference.admission
        body["admission"] = {
            "active": admission.active,
            "queued": admission.queued,
            "capacity": admission.capacity,
        }
        if admission.draining:
            body["status"] = "draining"
            # the drain snapshot names the membership state too: a load
            # balancer pulling this node out should know whether the rest
            # of the ring it routes to is at full membership
            body["admission"]["epoch"] = body.get("epoch", 0)
            body["admission"]["quarantine"] = list(
                body.get("quarantine") or ()
            )
        # fleet view: per-replica health snapshots aggregated at the front
        # door.  Serving capacity below fleet size is "degraded" (some
        # replica is down/draining); zero serving replicas wins outright —
        # the single-ring fields above describe only the primary
        if self.fleet is not None:
            replicas = [h.snapshot() for h in self.fleet.handles()]
            serving = sum(
                1 for h in self.fleet.handles()
                if h.serving and getattr(h.inference, "ready", False)
            )
            body["fleet"] = {
                "size": len(replicas),
                "serving": serving,
                "replicas": replicas,
            }
            if serving == 0:
                body["status"] = "draining" if admission.draining else "degraded"
            elif serving < len(replicas):
                if body.get("status") == "ok":
                    body["status"] = "degraded"
            elif body.get("status") == "draining" and serving > 0:
                # the PRIMARY is draining but other replicas still serve:
                # the front door as a whole is degraded, not out
                body["status"] = "degraded"
        return web.json_response(body)

    async def metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition of the process-global registry."""
        from dnet_tpu.obs.http import metrics_response

        return await metrics_response(request)

    async def _fan_out_shards(self, fetch) -> tuple[list, list]:
        """Shared httpx fan-out over the discovered shards (cluster
        metrics + cluster timeline): one AsyncClient with the obs scrape
        timeout, `fetch(client, device)` per device gathered concurrently,
        None results (unreachable / not-found / malformed) dropped.
        Returns (devices, results)."""
        import httpx

        from dnet_tpu.config import get_settings

        devices = await self.cluster_manager.scan_devices()
        timeout = get_settings().obs.cluster_scrape_timeout_s
        async with httpx.AsyncClient(timeout=timeout) as client:
            results = await asyncio.gather(
                *(fetch(client, d) for d in devices)
            )
        return devices, [r for r in results if r is not None]

    async def cluster_metrics(self, request: web.Request) -> web.Response:
        """Federated exposition: every healthy shard's /metrics plus this
        process's registry, each sample re-labeled with `node="<id>"` and
        merged into one Prometheus v0.0.4 document (obs/federation.py).
        Unreachable shards are skipped — and visible as
        `dnet_federation_scrape_ok{node=...} 0` in the API section."""
        from dnet_tpu.obs import (
            CONTENT_TYPE_LATEST,
            get_registry,
            get_slo_tracker,
            metric,
        )
        from dnet_tpu.obs.federation import federate

        sections: list[tuple[str, str]] = []
        if self.cluster_manager is not None:
            import httpx

            scrape_ok = metric("dnet_federation_scrape_ok")

            async def fetch(client, d):
                url = f"http://{d.host}:{d.http_port}/metrics"
                try:
                    r = await client.get(url)
                    r.raise_for_status()
                except httpx.HTTPError as exc:
                    log.warning(
                        "cluster metrics scrape of %s failed: %s",
                        d.instance, exc,
                    )
                    scrape_ok.labels(peer=d.instance).set(0.0)
                    return None
                scrape_ok.labels(peer=d.instance).set(1.0)
                return (d.instance, r.text)

            devices, scraped = await self._fan_out_shards(fetch)
            # a peer that left discovery is no longer scraped at all:
            # zero its gauge so `scrape_ok == 1` means "seen THIS scrape"
            current = {d.instance for d in devices}
            for gone in self._scraped_peers - current:
                scrape_ok.labels(peer=gone).set(0.0)
            self._scraped_peers |= current
            sections.extend(scraped)
        # fleet mode: in-process replicas share this registry (the
        # replica-labeled dnet_fleet_* families are already in the api
        # section), but their admission pictures are per-replica state the
        # registry cannot carry — synthesize one section of replica-labeled
        # gauges so queue skew between replicas shows up in one scrape
        if self.fleet is not None:
            lines = [
                "# HELP dnet_fleet_admission_slots Per-replica admission "
                "occupancy at scrape time (fleet front door)",
                "# TYPE dnet_fleet_admission_slots gauge",
            ]
            for h in self.fleet.handles():
                snap = h.snapshot()
                for field in ("active", "queued", "capacity"):
                    lines.append(
                        f'dnet_fleet_admission_slots{{replica='
                        f'"{h.replica_id}",kind="{field}"}} '
                        f'{float(snap["admission"][field])}'
                    )
            sections.append(("fleet", "\n".join(lines) + "\n"))
        # the API section LAST-built but FIRST-emitted: exposing after the
        # scrapes lets this very response carry their scrape_ok outcomes
        get_slo_tracker().snapshot()
        sections.insert(0, ("api", get_registry().expose()))
        body, skipped = federate(sections)
        for line in skipped:
            log.warning("cluster metrics: dropped unparseable line %s", line)
        return web.Response(
            body=body.encode("utf-8"),
            headers={"Content-Type": CONTENT_TYPE_LATEST},
        )

    async def debug_timeline(self, request: web.Request) -> web.Response:
        """One completed (or in-flight) request's flight-recorder spans —
        rid is the response id (`chatcmpl-...` or the completions-endpoint
        `cmpl-...` form); the recorder keeps the most recent requests, so
        recent rids resolve and ancient ones 404.  With `?cluster=1` the
        response is the MERGED cluster timeline: every shard's spans for
        the rid are fetched over their HTTP servers, skew-corrected onto
        this node's clock, and interleaved with the API's own spans."""
        from dnet_tpu.obs.critical_path import critical_path_section
        from dnet_tpu.obs.http import find_timeline

        rid = request.match_info["rid"]
        timeline = find_timeline(rid)
        cluster = request.query.get("cluster", "").strip().lower()
        if cluster in ("1", "true", "yes", "on"):
            stitched = await self._stitched_timeline(rid, timeline)
            if stitched is None:
                return _json_error(
                    404, f"no recorded timeline for {rid!r} on any node",
                    "not_found",
                )
            stitched["critical_path"] = critical_path_section(stitched)
            return web.json_response(stitched)
        if timeline is None:
            return _json_error(404, f"no recorded timeline for {rid!r}",
                               "not_found")
        payload = dict(timeline)
        payload["critical_path"] = critical_path_section(timeline)
        return web.json_response(payload)

    async def _stitched_timeline(
        self, rid: str, local: Optional[dict]
    ) -> Optional[dict]:
        """Fetch + stitch the shard halves of one request's timeline
        (None when no node recorded anything for the rid).

        Each shard fetch doubles as the clock probe correcting it: the
        response's `t_wall` bracketed by this node's wall clock yields an
        NTP-midpoint offset (obs/clock.py), so span times land on the API
        clock with error bounded by half the fetch round trip."""
        from dnet_tpu.obs.clock import offset_from_probe, stitch_timelines

        # shards key spans by the internal nonce; resolve the public
        # `cmpl-...` alias the same way the local lookup does
        internal = (local or {}).get("rid") or (
            "chat" + rid if rid.startswith("cmpl-") else rid
        )
        remotes = []
        if self.cluster_manager is not None:
            import httpx

            async def fetch(client, d):
                url = (
                    f"http://{d.host}:{d.http_port}"
                    f"/v1/debug/timeline/{internal}"
                )
                t0 = time.time()
                try:
                    r = await client.get(url)
                    t1 = time.time()
                    if r.status_code == 404:
                        return None  # this shard saw no frame for rid
                    r.raise_for_status()
                    tl = r.json()
                except (httpx.HTTPError, ValueError) as exc:
                    log.warning(
                        "cluster timeline fetch from %s failed: %s",
                        d.instance, exc,
                    )
                    return None
                try:
                    est = offset_from_probe(t0, float(tl["t_wall"]), t1)
                    tl["t_unix"] = float(tl["t_unix"])
                    assert isinstance(tl["spans"], list)
                except (KeyError, TypeError, ValueError, AssertionError):
                    # a body we cannot place on our clock (or without
                    # spans) must not 500 the whole merged view
                    log.warning(
                        "cluster timeline from %s malformed; skipping",
                        d.instance,
                    )
                    return None
                return (d.instance, tl, est)

            _devices, remotes = await self._fan_out_shards(fetch)
        if local is None and not remotes:
            return None
        return stitch_timelines(local, remotes, rid=internal)

    async def debug_sched(self, request: web.Request) -> web.Response:
        """Scheduler tick flight-recorder ring (sched/flight.py): per-tick
        token-budget use/waste, prefill/decode split, queue depths by
        state, preemptions, and KV block-pool occupancy.  `?last=N` trims
        the record list to the most recent N ticks."""
        from dnet_tpu.sched.flight import get_tick_recorder

        snap = get_tick_recorder().snapshot()
        last = request.query.get("last", "").strip()
        if last:
            try:
                n = max(0, int(last))
            except ValueError:
                return _json_error(400, "last must be an integer")
            snap["records"] = snap["records"][-n:] if n else []
        return web.json_response(snap)

    async def debug_events(self, request: web.Request) -> web.Response:
        """Query the structured wide-event ring (obs/events.py):
        `?rid=` one request's events (resume segments join their base rid),
        `?name=` one vocabulary entry (400 on an unknown name — typos must
        be loud, not silently empty), `?last_s=N` a trailing window.
        `?cluster=1` additionally fetches every shard's ring — each fetch
        doubling as the clock probe that rebases the shard's `t_unix` onto
        this node's clock — and returns the merged, time-ordered set."""
        from dnet_tpu.obs.events import get_event_ring, merge_remote_events
        from dnet_tpu.obs.phases import EVENT_NAMES

        rid = request.query.get("rid", "").strip()
        name = request.query.get("name", "").strip()
        if name and name not in EVENT_NAMES:
            return _json_error(
                400,
                f"unknown event name {name!r} (one of {sorted(EVENT_NAMES)})",
            )
        last_raw = request.query.get("last_s", "").strip()
        try:
            last_s = float(last_raw) if last_raw else 0.0
        except ValueError:
            return _json_error(400, "last_s must be a number")
        ring = get_event_ring()
        events = ring.query(rid=rid, name=name, last_s=last_s)
        dropped = ring.dropped
        cluster = request.query.get("cluster", "").strip().lower()
        if cluster in ("1", "true", "yes", "on") and (
            self.cluster_manager is not None
        ):
            import httpx

            from dnet_tpu.obs.clock import offset_from_probe

            async def fetch(client, d):
                url = f"http://{d.host}:{d.http_port}/v1/debug/events"
                params = {}
                if rid:
                    params["rid"] = rid
                if name:
                    params["name"] = name
                if last_s:
                    params["last_s"] = str(last_s)
                t0 = time.time()
                try:
                    r = await client.get(url, params=params)
                    t1 = time.time()
                    r.raise_for_status()
                    body = r.json()
                    est = offset_from_probe(t0, float(body["t_wall"]), t1)
                    remote = body["events"]
                    assert isinstance(remote, list)
                except (httpx.HTTPError, ValueError, KeyError,
                        TypeError, AssertionError) as exc:
                    log.warning(
                        "cluster events fetch from %s failed: %s",
                        d.instance, exc,
                    )
                    return None
                return (d.instance, remote, est)

            _devices, remotes = await self._fan_out_shards(fetch)
            # shard drop counts stay shard-local (each ring reports its
            # own loss); the merged view reports only this node's
            events = merge_remote_events(events, remotes)
        return web.json_response({"events": events, "dropped": dropped})

    async def debug_fleet(self, request: web.Request) -> web.Response:
        """Fleet routing introspection: the affinity table, per-replica
        health/load snapshots, and the epoch clock — the operator's view
        of why requests land where they land.  `{"fleet": null}` outside
        fleet mode (DNET_FLEET unset/1), mirroring /v1/topology's shape."""
        if self.fleet is None:
            return web.json_response({"fleet": None})
        return web.json_response({"fleet": self.fleet.snapshot()})

    async def debug_trace(self, request: web.Request) -> web.Response:
        """One request as Chrome trace-event / Perfetto JSON
        (obs/trace.py).  `?cluster=1` stitches every shard's spans in
        first, so the export carries one process track per node with flow
        arrows following the rid across hops.  `?format=` accepts only
        `perfetto` (the sole format) — anything else is a 400 so a typo'd
        format is loud, not silently perfetto."""
        from dnet_tpu.obs.events import get_event_ring
        from dnet_tpu.obs.http import find_timeline
        from dnet_tpu.obs.trace import export_trace
        from dnet_tpu.sched.flight import get_tick_recorder

        fmt = request.query.get("format", "perfetto").strip().lower()
        if fmt not in ("perfetto", "chrome"):
            return _json_error(400, f"unknown trace format {fmt!r}")
        rid = request.match_info["rid"]
        timeline = find_timeline(rid)
        cluster = request.query.get("cluster", "").strip().lower()
        if cluster in ("1", "true", "yes", "on"):
            timeline = await self._stitched_timeline(rid, timeline)
        if timeline is None:
            return _json_error(404, f"no recorded timeline for {rid!r}",
                               "not_found")
        # log<->trace correlation: the request's wide events render as
        # instant markers on the same clock as its spans (resume-suffixed
        # rids resolve through the same alias as the timeline lookup)
        internal = timeline.get("rid") or rid
        return web.json_response(
            export_trace(
                [timeline],
                tick_records=get_tick_recorder().snapshot()["records"],
                wide_events=get_event_ring().query(rid=internal),
            )
        )

    async def debug_trace_window(self, request: web.Request) -> web.Response:
        """Serving-window Perfetto dump: every timeline the recorder still
        retains whose request began in the last `last_s` seconds (default
        DNET_OBS_TRACE_WINDOW_S), plus the tick-record counter tracks."""
        from dnet_tpu.config import get_settings
        from dnet_tpu.obs import get_recorder
        from dnet_tpu.obs.trace import export_trace
        from dnet_tpu.sched.flight import get_tick_recorder

        last_raw = request.query.get("last_s", "").strip()
        try:
            last_s = (
                float(last_raw) if last_raw
                else get_settings().obs.trace_window_s
            )
        except ValueError:
            return _json_error(400, "last_s must be a number")
        recorder = get_recorder()
        timelines = [
            tl
            for rid in recorder.request_ids_since(time.time() - last_s)
            if (tl := recorder.timeline(rid)) is not None
        ]
        from dnet_tpu.obs.events import get_event_ring

        return web.json_response(
            export_trace(
                timelines,
                tick_records=get_tick_recorder().snapshot()["records"],
                wide_events=get_event_ring().query(last_s=last_s),
            )
        )
