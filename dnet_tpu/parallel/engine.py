"""MeshEngine: serve a whole model on a pp x tp x dp mesh as ONE XLA program.

The flagship TPU-native serving path (SURVEY.md §7 stage 4): where the
reference runs N shard processes exchanging gRPC frames, chips of one slice
form a Mesh and every decode step — all pipeline stages, tensor-parallel
matmuls, the activation hops (`lax.ppermute` over ICI) and the final logits —
is a single jitted step.  Exposes the LocalEngine session surface
(prefill_and_sample / decode_step / sessions / token_result), so the API
node's LocalAdapter drives it unchanged.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dnet_tpu.core.engine import LocalEngine, Session, bucket_length
from dnet_tpu.core.kvcache import init_cache
from dnet_tpu.core.sampler import SampleResult
from dnet_tpu.core.types import DecodingParams
from dnet_tpu.models import ModelConfig, get_ring_model_cls
from dnet_tpu.parallel.mesh import build_mesh
from dnet_tpu.parallel.ring import (
    make_ring_chunk_fn,
    make_ring_decode_fn,
    place_ring_state,
)
from dnet_tpu.utils.checkpoint import Checkpoint
from dnet_tpu.utils.logger import get_logger

log = get_logger()


class MeshEngine:
    """LocalEngine-compatible engine executing the pipelined ring in-slice.

    Session/sampling invariants are LocalEngine's own methods, borrowed via
    duck typing — one implementation, two execution substrates.
    """

    token_result = staticmethod(LocalEngine.token_result)
    prefill_and_sample = LocalEngine.prefill_and_sample
    _sample_with_counts = LocalEngine._sample_with_counts
    end_session = LocalEngine.end_session
    sweep_sessions = LocalEngine.sweep_sessions
    reset = LocalEngine.reset
    # paged KV is a Local/Batched engine feature (mesh caches are sharded);
    # the borrowed session/decode drivers consult these and no-op
    kv_pool = None
    _paged_ensure = LocalEngine._paged_ensure
    _paged_release = LocalEngine._paged_release
    # chunked-scan decode: the ring chunk program (make_ring_chunk_fn) keeps
    # LocalEngine's (packed, last_token, kv, key, counts) contract, so the
    # dispatch/read/pipelining machinery is borrowed verbatim — one
    # implementation, two execution substrates
    DECODE_CHUNK_BUCKETS = LocalEngine.DECODE_CHUNK_BUCKETS
    decode_chunk_dispatch = LocalEngine.decode_chunk_dispatch
    decode_chunk_read = LocalEngine.decode_chunk_read
    decode_chunk = LocalEngine.decode_chunk
    pending_chunks = LocalEngine.pending_chunks
    pending_width = LocalEngine.pending_width
    WARM_DECODINGS = LocalEngine.WARM_DECODINGS
    warm_chunks = LocalEngine.warm_chunks
    # speculative decoding: the ring verify program (make_ring_spec_fn)
    # keeps LocalEngine's _spec_step contract, so the eligibility gates and
    # the whole decode_spec driver are borrowed unchanged
    spec_lookahead = 0
    spec_eligible = LocalEngine.spec_eligible
    spec_worthwhile = LocalEngine.spec_worthwhile
    SPEC_WARMUP_BLOCKS = LocalEngine.SPEC_WARMUP_BLOCKS
    SPEC_MIN_TOKENS_PER_BLOCK = LocalEngine.SPEC_MIN_TOKENS_PER_BLOCK
    decode_spec = LocalEngine.decode_spec
    _commit_prompt_hist = LocalEngine._commit_prompt_hist

    def __init__(
        self,
        model_dir: str | Path,
        pp: int = 0,
        tp: int = 1,
        dp: int = 1,
        sp: int = 1,
        batch: int = 1,
        max_seq: int = 2048,
        param_dtype: str = "bfloat16",
        kv_dtype: Optional[str] = None,
        kv_quant_bits: int = 0,
        kv_ttl_s: float = 600.0,
        devices: Optional[Sequence] = None,
        weight_quant_bits: int = 0,
        quant_group: int = 0,  # 0 = quantizer default; must divide in/tp
        prefix_cache_size: int = 0,
        spec_lookahead: int = 0,
    ):
        self.ckpt = Checkpoint(model_dir)
        self.config = ModelConfig.from_hf(self.ckpt.config)
        model_cls = get_ring_model_cls(self.config.model_type)
        self.model = model_cls(self.config, range(self.config.num_hidden_layers))
        L = self.config.num_hidden_layers
        # segmented models zero-pad their stacks to pp divisibility — per
        # segment for multi-lap rings (ring_phases > 1), chunk-aligned for
        # interleaved layouts (pp_pad_chunks, models/qwen3_moe.py r5) — so
        # L need not divide evenly
        segmented = (
            getattr(self.model, "ring_phases", 1) > 1
            or getattr(self.model, "pp_pad_chunks", False)
        )
        if pp <= 0:  # 0 = infer: use every remaining device for pipeline stages
            n_dev = len(list(devices) if devices is not None else jax.devices())
            pp = max(n_dev // (tp * dp * sp), 1)
            while pp > 1 and L % pp != 0 and not segmented:
                pp -= 1
        if L % pp != 0 and not segmented:
            raise ValueError(f"pp={pp} must divide num_layers={L}")
        if sp > 1 and max_seq % sp != 0:
            raise ValueError(f"sp={sp} must divide max_seq={max_seq}")
        self.mesh = build_mesh(pp=pp, tp=tp, dp=dp, sp=sp, devices=devices)
        self.pp, self.tp, self.dp, self.sp = pp, tp, dp, sp
        self.batch = batch * dp
        self.max_seq = max_seq
        self.param_dtype = jnp.dtype(param_dtype)
        self.kv_dtype = kv_dtype or param_dtype
        self.kv_quant_bits = kv_quant_bits
        self.weight_quant_bits = weight_quant_bits
        self.quant_group = quant_group
        if weight_quant_bits and not self.model.supports_weight_quant:
            raise NotImplementedError(
                f"weight quantization not supported for {self.config.model_type}"
            )
        self.kv_ttl_s = kv_ttl_s
        self.sessions: Dict[str, Session] = {}
        self.plan = type("plan", (), {"streams_weights": False, "name": "fit"})()
        # the borrowed decode_spec driver branches on self.draft (draft-MODEL
        # speculation is LocalEngine-only); without the attribute the first
        # verify block dies on AttributeError mid-stream
        self.draft = None
        self.prefix_cache = None
        if prefix_cache_size > 0:
            # snapshots stay mesh-sharded: restore is a copy with the same
            # NamedSharding, no host round-trip
            from dnet_tpu.core.prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(prefix_cache_size)

        self._load_params()
        self._step = make_ring_decode_fn(self.model, self.mesh, self._host_window)
        self._decode_chunk = make_ring_chunk_fn(
            self.model, self.mesh, self._host_window
        )
        self.spec_lookahead = int(spec_lookahead)
        if self.spec_lookahead > 0:
            from dnet_tpu.parallel.ring import make_ring_spec_fn

            self._spec_step = make_ring_spec_fn(
                self.model, self.mesh, self._host_window, self.spec_lookahead
            )
        log.info(
            "MeshEngine: %s over mesh pp=%d tp=%d dp=%d sp=%d (%d devices)",
            self.config.model_type, pp, tp, dp, sp, pp * tp * dp * sp,
        )

    @classmethod
    def from_params(
        cls,
        config: ModelConfig,
        window_params,
        edge_params,
        *,
        pp: int = 0,
        tp: int = 1,
        dp: int = 1,
        sp: int = 1,
        batch: int = 1,
        max_seq: int = 2048,
        param_dtype: str = "bfloat16",
        kv_dtype: Optional[str] = None,
        kv_quant_bits: int = 0,
        kv_ttl_s: float = 600.0,
        devices: Optional[Sequence] = None,
    ) -> "MeshEngine":
        """Build a mesh engine around already-materialised (host) params —
        the zero-egress bench path (mirror of LocalEngine.from_params): the
        serving hot loop and shardings are identical, only weight
        provenance differs.  Params may already be quantized."""
        self = cls.__new__(cls)
        self.ckpt = None
        self.config = config
        model_cls = get_ring_model_cls(config.model_type)
        self.model = model_cls(config, range(config.num_hidden_layers))
        L = config.num_hidden_layers
        segmented = (
            getattr(self.model, "ring_phases", 1) > 1
            or getattr(self.model, "pp_pad_chunks", False)
        )
        if pp <= 0:
            n_dev = len(list(devices) if devices is not None else jax.devices())
            pp = max(n_dev // (tp * dp * sp), 1)
            while pp > 1 and L % pp != 0 and not segmented:
                pp -= 1
        if L % pp != 0 and not segmented:
            raise ValueError(f"pp={pp} must divide num_layers={L}")
        self.mesh = build_mesh(pp=pp, tp=tp, dp=dp, sp=sp, devices=devices)
        self.pp, self.tp, self.dp, self.sp = pp, tp, dp, sp
        self.batch = batch * dp
        self.max_seq = max_seq
        self.param_dtype = jnp.dtype(param_dtype)
        self.kv_dtype = kv_dtype or param_dtype
        self.kv_quant_bits = kv_quant_bits
        # params may arrive pre-quantized: detect for honest introspection,
        # and run the same actionable divisibility check as __init__
        from dnet_tpu.ops.quant import is_quantized

        quantized = isinstance(window_params, dict) and any(
            isinstance(v, dict) and is_quantized(v)
            for v in window_params.values()
        )
        self.weight_quant_bits = 8 if quantized else 0
        self.quant_group = 0
        self.kv_ttl_s = kv_ttl_s
        self.sessions = {}
        self.plan = type("plan", (), {"streams_weights": False, "name": "fit"})()
        self.draft = None  # mesh spec drafts by prompt-lookup only
        self.prefix_cache = None
        if isinstance(window_params, dict):
            self._check_quant_sharding(window_params)
        m = self.model
        self._n_kv_layers = len(m.layers)
        self._host_window = window_params
        kv0 = m.init_kv(
            self._n_kv_layers, self.batch, self.max_seq, self.kv_dtype,
            quant_bits=self.kv_quant_bits, rotating=(self.sp == 1),
        )
        self.window_params, self.edge_params, self._kv_template = place_ring_state(
            window_params, edge_params, kv0, self.mesh
        )
        self._step = make_ring_decode_fn(self.model, self.mesh, self._host_window)
        self._decode_chunk = make_ring_chunk_fn(
            self.model, self.mesh, self._host_window
        )
        return self

    def _check_quant_sharding(self, stacked: dict) -> None:
        """Fail fast with an actionable message when the scale-group axis of
        an in-sharded (row-parallel) weight cannot split over tp — otherwise
        the error surfaces as an opaque NamedSharding divisibility failure
        deep in place_ring_state."""
        from dnet_tpu.ops.quant import is_quantized
        from dnet_tpu.parallel.mesh import _ROW_PARALLEL

        if self.tp <= 1:
            return
        for name, w in stacked.items():
            if name in _ROW_PARALLEL and is_quantized(w):
                g = w["s"].shape[-2]
                if g % self.tp != 0:
                    raise ValueError(
                        f"weight {name!r} has {g} dequant scale groups, not "
                        f"divisible by tp={self.tp}: pass quant_group=G with "
                        f"G dividing in/tp (e.g. DNET_API_WEIGHT_QUANT_GROUP)"
                    )

    # ---- loading ------------------------------------------------------
    def _load_params(self) -> None:
        t0 = time.perf_counter()
        m = self.model
        per_layer = [m.map_layer(self.ckpt.load_layer_raw(a)) for a in m.layers]
        stacked = m.stack_layers(per_layer)
        if self.weight_quant_bits:
            # quantize raw values; the TP/PP shardings apply unchanged to the
            # {"q"/"q4","s"} leaves (scales share the weight's axis layout),
            # and groups stay rank-local because quant_group divides in/tp
            stacked = m.quantize_params(
                stacked,
                self.weight_quant_bits,
                scale_dtype=self.param_dtype,
                group_size=self.quant_group,
            )
            self._check_quant_sharding(stacked)

        def cast(a):
            arr = np.asarray(a)
            if np.issubdtype(arr.dtype, np.floating):
                import ml_dtypes

                target = (
                    ml_dtypes.bfloat16
                    if self.param_dtype == jnp.bfloat16
                    else self.param_dtype
                )
                arr = arr.astype(target)
            return arr

        # segmented models: zero-pad each segment's layer axis to a pp
        # multiple (exact residual no-ops); the KV cache then holds the
        # padded layer count, laid out per-rank (dense rows then moe rows)
        self._n_kv_layers = len(m.layers)
        if (
            getattr(m, "ring_phases", 1) > 1
            or getattr(m, "pp_pad_chunks", False)
        ):
            stacked, self._n_kv_layers = m.pad_mesh_segments(stacked, self.pp)
        self._host_window = jax.tree.map(cast, stacked)
        edge_raw = m.map_edge(self.ckpt.load_edge_raw())
        if self.weight_quant_bits:
            edge_raw = m.quantize_edge(
                edge_raw, self.weight_quant_bits, scale_dtype=self.param_dtype,
                group_size=self.quant_group,
            )
        edge = jax.tree.map(cast, edge_raw)
        kv0 = m.init_kv(
            self._n_kv_layers, self.batch, self.max_seq, self.kv_dtype,
            quant_bits=self.kv_quant_bits, rotating=(self.sp == 1),
        )
        self.window_params, self.edge_params, self._kv_template = place_ring_state(
            self._host_window, edge, kv0, self.mesh
        )
        log.info(
            "[PROFILE] mesh-placed %d layers in %.2fs",
            len(m.layers), time.perf_counter() - t0,
        )

    # ---- sessions -----------------------------------------------------
    def new_session(
        self, nonce: str, seed: Optional[int] = None, kv=None, pos: int = 0
    ) -> Session:
        """kv/pos: seed from a prefix-cache snapshot (already mesh-sharded)
        instead of allocating + placing a zero cache it would drop."""
        if seed is None:
            seed = int.from_bytes(os.urandom(4), "little")
        if kv is None:
            kv0 = self.model.init_kv(
                self._n_kv_layers, self.batch, self.max_seq, self.kv_dtype,
                quant_bits=self.kv_quant_bits, rotating=(self.sp == 1),
            )
            _, _, kv = place_ring_state({}, {}, kv0, self.mesh)
        sess = Session(
            nonce=nonce,
            kv=kv,
            pos=pos,
            key=jax.random.key(seed),
            counts=jnp.zeros((self.batch, self.config.vocab_size), dtype=jnp.int32),
            hist=(
                jnp.zeros((self.batch, self.max_seq), dtype=jnp.int32)
                if self.spec_lookahead > 0
                else None
            ),
        )
        self.sessions[nonce] = sess
        return sess

    def close(self) -> None:
        self.sessions.clear()

    # ---- inference ----------------------------------------------------
    def _forward_ring(self, sess: Session, tokens_np: np.ndarray, last_idx: int):
        logits, sess.kv = self._step(
            self.window_params, self.edge_params, jnp.asarray(tokens_np),
            sess.kv, jnp.int32(sess.pos), jnp.int32(last_idx),
        )
        return logits

    def prefill(self, nonce: str, prompt_ids: Sequence[int], seed: Optional[int] = None):
        full_ids = list(prompt_ids)
        if not full_ids:
            raise ValueError("empty prompt")
        sess = self.sessions.get(nonce)
        fresh = sess is None
        # validate against the FULL prompt BEFORE any session mutation: a
        # too-long prompt must not leave a half-restored session behind
        start = 0 if sess is None else sess.pos
        if start + len(full_ids) > self.max_seq:
            raise ValueError(
                f"prompt length {start + len(full_ids)} exceeds max_seq "
                f"{self.max_seq}"
            )
        if sess is None:
            hit = (
                self.prefix_cache.lookup(full_ids)
                if self.prefix_cache is not None
                else None
            )
            if hit is not None:
                n, kv_copy = hit  # snapshot keeps the template's sharding
                sess = self.new_session(nonce, seed, kv=kv_copy, pos=n)
                prompt_ids = full_ids[n:]  # >= 1 token left by construction
            else:
                sess = self.new_session(nonce, seed)
        self._commit_prompt_hist(sess, full_ids, prompt_ids)
        T = len(prompt_ids)
        Tpad = min(bucket_length(T), self.max_seq - sess.pos)
        tokens = np.zeros((self.batch, Tpad), dtype=np.int32)
        tokens[:, :T] = np.asarray(prompt_ids, dtype=np.int32)
        logits = self._forward_ring(sess, tokens, T - 1)
        sess.pos += T
        sess.last_used = time.time()
        if self.prefix_cache is not None and fresh and sess.pos == len(full_ids):
            self.prefix_cache.store(full_ids, sess.kv)
        return logits

    def decode_step(self, nonce: str, token_id: int, decoding: DecodingParams) -> SampleResult:
        sess = self.sessions[nonce]
        if sess.pos >= self.max_seq:
            raise ValueError(f"sequence length {sess.pos} reached max_seq {self.max_seq}")
        tokens = np.full((self.batch, 1), token_id, dtype=np.int32)
        logits = self._forward_ring(sess, tokens, 0)
        res = self._sample_with_counts(sess, logits, decoding)
        sess.pos += 1
        sess.last_used = time.time()
        return res

    def generate(self, prompt_ids, decoding=None, max_tokens=256, eos_token_ids=None, nonce="mesh"):
        """Same loop as LocalEngine.generate (shared via duck-typed surface)."""
        return LocalEngine.generate(
            self, prompt_ids, decoding, max_tokens, eos_token_ids, nonce
        )

    def hidden_states(self, prompt_ids: Sequence[int]) -> np.ndarray:
        """Embeddings primitive through the mesh ring (LocalEngine's
        contract: float32 [T, D] of final-norm'd hidden states).  The ring
        pass runs over a throwaway KV; the program compiles lazily on the
        first embeddings request."""
        ids = list(prompt_ids)
        if not ids:
            raise ValueError("empty embeddings input")
        if len(ids) > self.max_seq:
            raise ValueError(
                f"input length {len(ids)} exceeds max_seq {self.max_seq}"
            )
        if not hasattr(self, "_hidden_fn"):
            from dnet_tpu.parallel.ring import make_ring_hidden_fn

            self._hidden_fn = make_ring_hidden_fn(
                self.model, self.mesh, self._host_window
            )
            # throwaway KV operand, built ONCE: the hidden fn never donates
            # it and t_real masks its (stale) contents, so every embeddings
            # request reuses the same placed buffers
            kv0 = self.model.init_kv(
                self._n_kv_layers, self.batch, self.max_seq, self.kv_dtype,
                quant_bits=self.kv_quant_bits, rotating=(self.sp == 1),
            )
            _, _, self._hidden_kv = place_ring_state({}, {}, kv0, self.mesh)
        T = len(ids)
        Tpad = min(bucket_length(T), self.max_seq)
        tokens = np.zeros((self.batch, Tpad), dtype=np.int32)
        tokens[:, :T] = np.asarray(ids, dtype=np.int32)
        h, _ = self._hidden_fn(
            self.window_params, self.edge_params, jnp.asarray(tokens),
            self._hidden_kv, jnp.int32(0), jnp.int32(T - 1),
        )
        return np.asarray(h[0, :T], dtype=np.float32)
