"""Intra-shard tensor parallelism over a ("batch", "model") mesh.

ROADMAP item 3's TP half (DNET_TP=N, default 1 = today's behavior): a ring
shard's attention heads and MLP matrices shard across its host-local chips
with NamedSharding over a two-axis ("batch", "model") mesh — the classic
cross-replica weight-sharding layout (PAPERS.md, arxiv 2004.13336) — while
activations keep hopping host-to-host over the gRPC ring.  A v5litepod-4
host stops serving as a 1-chip hop: the solver places it as ONE mesh slice
(parallel/solver.py mesh-slice placement) and its whole window runs tp=4.

Three pieces live here:

- :func:`place_presharded` — weights load PRE-SHARDED: each chip's slice
  of each tensor is cut from the host (mmap-backed) array, cast, and
  uploaded individually, then assembled with
  ``jax.make_array_from_single_device_arrays``.  Neither the host cast
  buffer nor any single chip ever materializes a full tensor — load peak
  is 1/N per chip.  MeshShardEngine's loader routes through this too.
- the ("batch", "model") spec rules — the same column/row-parallel name
  sets as parallel/mesh.py, re-expressed on the 2-axis mesh; the KV cache
  (dense [L, B, S, KVH, Hd] AND pool-shaped [L, N, bt, KVH, Hd]) shards
  on the HEAD axis, so per-chip views keep the exact layout the PR 12
  ragged kernel reads — it runs per chip unchanged.
- :class:`TpEngine` — MeshShardEngine with the substrate hooks overridden:
  2-axis mesh, pre-sharded specs, and the per-layer collectives routed
  through the quantizable seam (parallel/tp_collectives.py) as a
  :class:`~dnet_tpu.parallel.tp_collectives.TpAxis`, so
  ``DNET_TP_COLLECTIVE=q8`` shrinks the intra-shard interconnect the way
  the PR 14 wire codec shrank the ring hops.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dnet_tpu.parallel.mesh import (
    _COL_PARALLEL,
    _EXPERT_SHARDED,
    _EXPERT_VECTORS,
    _HEAD_VECTORS,
    _ROW_PARALLEL,
)
from dnet_tpu.parallel.shard_mesh import MeshShardEngine
from dnet_tpu.parallel.tp_collectives import (
    MODE_LOSSLESS,
    TpAxis,
    collective_bytes,
    observe_collective_bytes,
    probe_collective_ms,
    resolve_collective_mode,
)
from dnet_tpu.utils.logger import get_logger

log = get_logger()

AXIS_BATCH, AXIS_MODEL = "batch", "model"


def tp_enabled_degree() -> int:
    """The configured DNET_TP degree (1 = off, today's behavior)."""
    from dnet_tpu.config import get_settings

    return max(int(get_settings().tp.tp), 1)


def build_tp_mesh(
    tp: int, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """A (batch=1, model=tp) mesh over the shard's local chips."""
    devices = list(devices if devices is not None else jax.devices())
    if tp > len(devices):
        raise ValueError(
            f"tp={tp} needs {tp} devices, have {len(devices)}"
        )
    grid = np.array(devices[:tp]).reshape(1, tp)
    return Mesh(grid, (AXIS_BATCH, AXIS_MODEL))


# ---- ("batch", "model") sharding rules ------------------------------------
# Same name sets as the 4-axis mesh (parallel/mesh.py); the stacked layer
# axis is UNSHARDED here (the pipeline is the gRPC ring outside the mesh)
# and tensor splits ride the "model" axis.


def tp_param_spec(name: str) -> P:
    if name in _COL_PARALLEL:
        return P(None, None, AXIS_MODEL)
    if name in _ROW_PARALLEL:
        return P(None, AXIS_MODEL, None)
    if name in _HEAD_VECTORS:
        return P(None, AXIS_MODEL)
    if name in _EXPERT_SHARDED:
        return P(None, AXIS_MODEL, None, None)
    if name in _EXPERT_VECTORS:
        return P(None, AXIS_MODEL, None)
    return P()  # norms, routers, kind scalars: replicate


def tp_window_specs(window_params: Dict) -> Dict:
    """Spec pytree for a stacked window (two-level segment layouts too)."""
    out: Dict = {}
    for k, v in window_params.items():
        if k in ("dense", "moe", "a", "b") and isinstance(v, dict):
            out[k] = {kk: tp_param_spec(kk) for kk in v}
        else:
            out[k] = tp_param_spec(k)
    return out


def tp_kv_spec() -> P:
    """KV sharded on the HEAD axis over "model" — one spec for BOTH rank-5
    cache layouts: the dense [L, B, S, KVH, Hd] session cache (B rides the
    size-1 batch axis) and the pool-shaped [L, N_blocks, bt, KVH, Hd]
    paged layout, whose per-chip view keeps exactly the shape the PR 12
    ragged kernel's block index map addresses — the kernel runs per chip
    unchanged, each chip attending its own KVH/tp heads."""
    return P(None, None, None, AXIS_MODEL, None)


# ---- pre-sharded placement ------------------------------------------------


def place_presharded(tree, mesh: Mesh, specs, cast=None):
    """Place a host pytree onto the mesh WITHOUT materializing full
    tensors: for every leaf, each device's slice is cut from the host
    array (a view into the mmap-backed checkpoint), optionally cast —
    slice-sized copies only — uploaded to its device, and the global
    array assembled from the per-device pieces.

    ``specs`` mirrors the tree one level deep (the window_param_specs
    layout: name -> spec, with segment dicts nested one more level); a
    spec covers every leaf of its subtree, which is how quantized weight
    dicts ({codes, scales}) inherit their tensor's split.
    """

    def place_leaf(a, spec: P):
        arr = np.asarray(a)
        sharding = NamedSharding(mesh, spec)
        shards = []
        for dev, idx in sharding.addressable_devices_indices_map(
            arr.shape
        ).items():
            sl = arr[idx]
            if cast is not None:
                sl = cast(sl)
            shards.append(jax.device_put(np.ascontiguousarray(sl), dev))
        return jax.make_array_from_single_device_arrays(
            arr.shape, sharding, shards
        )

    def place_subtree(subtree, spec):
        if isinstance(spec, dict):
            return {k: place_subtree(subtree[k], spec[k]) for k in subtree}
        return jax.tree.map(lambda leaf: place_leaf(leaf, spec), subtree)

    if not isinstance(specs, dict):
        return place_subtree(tree, specs)
    return {k: place_subtree(v, specs[k]) for k, v in tree.items()}


class TpEngine(MeshShardEngine):
    """A ring shard's compute core, tensor-parallel over ("batch","model").

    MeshShardEngine with the substrate hooks overridden: same jitted-fn
    surface, same Session contract, same ShardCompute hot loop — the
    window math runs SPMD over the 2-axis mesh with the per-layer
    all-reduces routed through the quantizable collective seam.  Greedy
    streams under the lossless mode are byte-identical to tp=1 (the
    parity contract tests/subsystems/test_tp_parity.py pins through the
    real HTTP server).
    """

    def __init__(
        self,
        model_dir: str | Path,
        layers: Sequence[int],
        tp: int = 1,
        devices: Optional[Sequence] = None,
        collective: str = "",
        collective_group_size: int = 0,
        **kwargs,
    ) -> None:
        if tp < 1:
            raise ValueError(f"tp={tp} must be positive")
        if kwargs.pop("sp", 1) != 1:
            raise ValueError(
                "TpEngine is tensor-parallel only; sequence parallelism "
                "stays on the shard_map substrate (parallel/shard_mesh.py)"
            )
        from dnet_tpu.config import get_settings

        devices = list(devices if devices is not None else jax.devices())
        w = get_settings().tp
        self.collective_mode = resolve_collective_mode(
            collective or w.tp_collective, devices=devices[:tp]
        )
        self.collective_group_size = int(
            collective_group_size or w.tp_group_size
        )
        self._coll_books = {"all_reduce": 0, "all_gather": 0}
        # grandparent init on purpose: MeshShardEngine.__init__ would
        # build the 4-axis mesh; everything else it does is LocalEngine's
        self.tp, self.sp = tp, 1
        self.mesh = build_tp_mesh(tp, devices)
        from dnet_tpu.core.engine import LocalEngine

        LocalEngine.__init__(
            self,
            model_dir,
            layers=list(layers),
            shard_mode=True,
            **kwargs,
        )
        from dnet_tpu.obs import metric

        metric("dnet_tp_degree").set(float(tp))
        if tp > 1:
            probe_collective_ms(
                self.mesh, AXIS_MODEL, self.config.hidden_size,
                self.param_dtype, self.collective_mode,
                self.collective_group_size,
            )

    def _build_fns(self) -> None:
        """The inherited program builders, with every jitted TP entry
        point instrumented under ONE declared label: a shape leak in the
        sharded window programs shows up as a climbing
        dnet_jit_compiles_total{fn="tp_window"} instead of a mystery
        per-hop latency cliff (the obs/jit.py contract; the flow lint's
        DL021/DL022 jit model seeds its wrapper set from JIT_FNS)."""
        from dnet_tpu.obs.jit import instrument_jit

        super()._build_fns()
        for attr in ("_hidden", "_hidden_round", "_embed_window",
                     "_hidden_tail", "_forward", "_decode", "_decode_chunk",
                     "_spec_step"):
            fn = getattr(self, attr, None)
            if fn is not None:
                setattr(self, attr, instrument_jit(fn, "tp_window"))

    # ---- substrate hooks ---------------------------------------------
    def _tp_axis(self):
        return TpAxis(
            AXIS_MODEL,
            mode=self.collective_mode,
            group_size=self.collective_group_size,
        )

    def _sp_axis(self):
        return None

    def _certify_axes(self):
        return (AXIS_BATCH,)

    def _window_specs_of(self, tree):
        return tp_window_specs(tree)

    def _kv_pspec(self):
        return tp_kv_spec()

    def _place_window(self, host_tree):
        return place_presharded(
            host_tree, self.mesh, self._window_specs_of(host_tree),
            cast=self._np_cast,
        )

    def _load_params(self) -> None:
        # head divisibility is a LOAD-time contract: a tp that does not
        # divide the q/kv head counts would shard a head across chips
        cfg = self.config
        heads = cfg.num_attention_heads or 0
        kv_heads = cfg.num_key_value_heads or heads
        for kind, n in (("attention", heads), ("kv", kv_heads)):
            if self.tp > 1 and n and n % self.tp != 0:
                raise ValueError(
                    f"tp={self.tp} does not divide {kind} heads ({n}); "
                    f"the solver clamps tp_degree to a divisor — pass one"
                )
        super()._load_params()

    # ---- collective byte accounting (host side, per dispatch) ---------
    def observe_step_collectives(self, tokens: int = 1) -> None:
        """Book the analytic interconnect bytes one window pass paid:
        2 all-reduces per layer over [B, T, D] activations (the models'
        out-proj and down-proj seams).  Called by ShardCompute after each
        dispatched frame — pure shape math, no device syncs."""
        if self.tp <= 1:
            return
        n_elem = max(tokens, 1) * self.config.hidden_size
        eb = np.dtype(self.param_dtype).itemsize
        nbytes = 2 * len(self.model.layers) * collective_bytes(
            "all_reduce", self.collective_mode, self.tp, n_elem, eb,
            self.collective_group_size,
        )
        observe_collective_bytes("all_reduce", nbytes)
