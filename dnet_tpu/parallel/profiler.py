"""Device profiling: per-chip capability microbenchmarks.

The analog of distilp's profiler (reference §2.7): measures achieved matmul
FLOP/s, HBM read bandwidth, and host->device transfer rate, plus memory
capacities — the solver's per-device cost-model inputs.  Quick mode runs
in-process in a few seconds; full mode (solver task) runs in a subprocess
like the reference's Metal-isolation trick (utils/profile_subproc.py:27-63).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np


def profile_device_quick(device=None) -> dict:
    import jax
    import jax.numpy as jnp

    dev = device or jax.devices()[0]

    # matmul FLOPs (bf16, MXU-shaped)
    N = 2048
    a = jnp.ones((N, N), dtype=jnp.bfloat16)
    b = jnp.ones((N, N), dtype=jnp.bfloat16)
    f = jax.jit(lambda x, y: x @ y)
    f(a, b).block_until_ready()
    t0 = time.perf_counter()
    iters = 8
    out = a
    for _ in range(iters):
        out = f(out, b)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    flops = 2 * N**3 * iters / dt

    # HBM read bandwidth: sum over a large array
    M = 64 * 1024 * 1024 // 2  # 64MB of bf16
    big = jnp.ones((M,), dtype=jnp.bfloat16)
    g = jax.jit(lambda x: jnp.sum(x, dtype=jnp.float32))
    g(big).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        g(big).block_until_ready()
    dt = time.perf_counter() - t0
    hbm_bw = M * 2 * iters / dt

    # host -> device transfer rate
    host = np.ones((32 * 1024 * 1024,), dtype=np.uint8)  # 32MB
    jax.device_put(host, dev).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(4):
        jax.device_put(host, dev).block_until_ready()
    h2d = host.nbytes * 4 / (time.perf_counter() - t0)

    mem = {}
    try:
        stats = dev.memory_stats() or {}
        mem = {
            "hbm_bytes": stats.get("bytes_limit", 0),
            "hbm_in_use": stats.get("bytes_in_use", 0),
        }
    except Exception:
        pass

    import psutil

    return {
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "platform": dev.platform,
        "flops_bf16": flops,
        "hbm_bw": hbm_bw,
        "host_to_hbm_bw": h2d,
        "host_ram_bytes": psutil.virtual_memory().total,
        # chips this host can put behind ONE ring node (mesh-backed shard,
        # parallel/shard_mesh.py); the solver aggregates the slice's
        # FLOPs/HBM through DeviceInfo.chip_count
        "local_device_count": jax.local_device_count(),
        **mem,
    }


def _profile_child(conn) -> None:
    try:
        result = profile_device_quick()
        conn.send({"ok": True, "profile": result})
    except Exception as exc:  # pragma: no cover - child-side
        conn.send({"ok": False, "error": str(exc)})
    finally:
        conn.close()


def profile_device_subprocess(timeout_s: float = 120.0) -> dict:
    """Run the microbench in a spawned child so device allocations die with
    the process (the reference's Metal-isolation trick,
    utils/profile_subproc.py:27-63).  Falls back in-process if the child
    cannot grab the accelerator (single-chip tunnels are exclusive)."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    proc = ctx.Process(target=_profile_child, args=(child,), daemon=True)
    proc.start()
    child.close()

    def _reap() -> None:
        proc.join(timeout=5)
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=5)

    result = None
    exc: Optional[Exception] = None
    try:
        if parent.poll(timeout_s):
            msg = parent.recv()
            if msg.get("ok"):
                result = msg["profile"]
            else:
                exc = RuntimeError(f"profiler child failed: {msg.get('error')}")
        else:
            exc = TimeoutError(f"device profile timed out after {timeout_s}s")
    except EOFError as eof:
        exc = eof
    finally:
        parent.close()
        # reap the child BEFORE any in-process fallback — on exclusive-access
        # devices a hung child would otherwise still hold the accelerator
        _reap()

    if result is not None:
        return result
    from dnet_tpu.utils.logger import get_logger

    get_logger().warning("subprocess profile unavailable (%s); running in-process", exc)
    return profile_device_quick()
