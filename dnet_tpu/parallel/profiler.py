"""Device profiling: per-chip capability microbenchmarks.

The analog of distilp's profiler (reference §2.7): measures achieved matmul
FLOP/s, HBM read bandwidth, and host->device transfer rate, plus memory
capacities — the solver's per-device cost-model inputs.  Quick mode runs
in-process in a few seconds; full mode (solver task) runs in a subprocess
like the reference's Metal-isolation trick (utils/profile_subproc.py:27-63).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np


def profile_device_quick(device=None) -> dict:
    import jax
    import jax.numpy as jnp

    dev = device or jax.devices()[0]

    # matmul FLOPs (bf16, MXU-shaped)
    N = 2048
    a = jnp.ones((N, N), dtype=jnp.bfloat16)
    b = jnp.ones((N, N), dtype=jnp.bfloat16)
    f = jax.jit(lambda x, y: x @ y)
    f(a, b).block_until_ready()
    t0 = time.perf_counter()
    iters = 8
    out = a
    for _ in range(iters):
        out = f(out, b)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    flops = 2 * N**3 * iters / dt

    # HBM read bandwidth: sum over a large array
    M = 64 * 1024 * 1024 // 2  # 64MB of bf16
    big = jnp.ones((M,), dtype=jnp.bfloat16)
    g = jax.jit(lambda x: jnp.sum(x, dtype=jnp.float32))
    g(big).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        g(big).block_until_ready()
    dt = time.perf_counter() - t0
    hbm_bw = M * 2 * iters / dt

    # host -> device transfer rate
    host = np.ones((32 * 1024 * 1024,), dtype=np.uint8)  # 32MB
    jax.device_put(host, dev).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(4):
        jax.device_put(host, dev).block_until_ready()
    h2d = host.nbytes * 4 / (time.perf_counter() - t0)

    mem = {}
    try:
        stats = dev.memory_stats() or {}
        mem = {
            "hbm_bytes": stats.get("bytes_limit", 0),
            "hbm_in_use": stats.get("bytes_in_use", 0),
        }
    except Exception:
        pass

    import psutil

    return {
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "platform": dev.platform,
        "flops_bf16": flops,
        "hbm_bw": hbm_bw,
        "host_to_hbm_bw": h2d,
        "host_ram_bytes": psutil.virtual_memory().total,
        **mem,
    }
