"""Staggered-microbatch pipelined ring: every pp rank does real work.

The sequential ring program (parallel/ring.py) runs PP stage-steps per token
with one rank's activation real at a time — (PP-1)/PP of the slice idles.
This module fills the pipeline the classic way, compiled into ONE XLA
program: M >= PP sequence slots are staggered across the pp ranks so that at
every stage-step each rank computes a *different* sequence's stage, and the
hidden states rotate one hop over ICI (`lax.ppermute`).  One "rotation" (M
stage-steps, a single dispatch) enters one new token per slot, exits one
sampled token per slot, and keeps every chip busy the whole time — the
steady state promised by the reference's k-round round-robin schedule
(src/dnet/api/utils.py:62-131), reached here inside a single jitted program.

Schedule (global step t, M slots, PP stages):
  - the token entering at step t belongs to slot  n(t) = t mod M
  - rank r is working on the token that entered at step t - r,
    i.e. slot (t - r) mod M
  - rank PP-1 finishes the token that entered at t-(PP-1): exit slot
    e(t) = (t - PP + 1) mod M; its logits are sampled ON DEVICE and the
    token is written to the entry buffer, so slot e's next entry (step
    t+1 when M == PP) needs no host round-trip.

Sampling inside the rotation matches LocalEngine's per-step key evolution
(split-before-sample per generated token), so a seeded request produces the
identical stream through either engine.

KV: per-slot caches live in one array with the slot folded into the batch
axis ([L, M*B, S, KVH, Hd]); each stage-step slices its slot out, applies
the stage, and writes it back (the write is a dynamic_update_slice into the
donated carry).  Garbage produced by idle slots lands only in idle slots'
rows and is overwritten by the next prefill.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dnet_tpu.utils.jax_compat import pcast_varying, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dnet_tpu.core.sampler import (
    MAX_LOGIT_BIAS,
    SampleParams,
    SampleResult,
    encode_logit_bias,
    sample,
)
from dnet_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    kv_spec,
    window_param_specs,
)


def _bcast_from_rank(x, axis_name: str, rank: int):
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == rank, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


# ---- multi-lap schedule ----------------------------------------------------
# Segmented models (deepseek ring_phases=2) need each token to traverse the
# ring `phases` times — lap p applies every rank's slice of segment p, so the
# global layer order stays all-dense-then-all-moe.  The schedule generalizes
# the single-lap rotation: a token occupies the ring for PHI = phases*PP
# stage-steps; rank 0 takes a NEW entry only on steps whose arriving token
# has finished its last lap, which happens in bursts of PP consecutive steps
# every PHI (entry_open); entries cycle the M slots round-robin; the token
# entering at step te exits at te + PHI - 1.  phases=1 reduces to the r2
# schedule exactly: entry_open always, slot(t) = t mod M, exit latency PP-1.


def _entry_open(t: int, pp: int, phases: int) -> bool:
    return (t % (phases * pp)) < pp


def _entry_slot(t: int, pp: int, phases: int, m: int) -> int:
    """Slot fed by the entry at step t (valid only when _entry_open)."""
    phi = phases * pp
    return ((t // phi) * pp + (t % phi)) % m


def resolve_pp(n_dev: int, tp: int, sp: int, n_layers: int) -> int:
    """Infer pp from the device budget: every remaining device becomes a
    pipeline stage, decremented until it divides the layer count (the same
    fallback as MeshEngine's inference).  Shared by the engine and the
    serving manager's precheck so both always agree on the resolved pp."""
    pp = max(n_dev // (tp * sp), 1)
    while pp > 1 and n_layers % pp != 0:
        pp -= 1
    return pp


def make_rotation_fn(
    model, mesh: Mesh, window_params, n_slots: int, batch: int = 1,
    n_steps: Optional[int] = None,
):
    """Build the jitted rotation program over `n_steps` stage-steps
    (default M = one rotation; R*M fuses R rotations into ONE dispatch —
    the chunked pipelined path: sampled tokens re-enter their slot on
    device, so the host pays one dispatch + one packed read per R tokens
    per slot instead of per rotation).

    Returned signature:
      (window_params, edge_params, x_state[PP,B,1,D], kv, tokens[M,B],
       pos_vec[M], pos_state[PP], live_state[PP], phase_state[PP],
       entry_open[n_steps], enter_live[n_steps], entry_slot[n_steps],
       exit_valid[n_steps], exit_slot[n_steps], sp_stack, keys[M,2]u32,
       counts[M,B,V], t0)
      -> (results: SampleResult leaves stacked [n_steps,B,...] in EXIT-STEP
          order, x_state, kv, tokens, pos_vec, pos_state, live_state,
          phase_state, keys, counts)

    enter_live is PER STEP (index j), not per slot: a slot's capacity can
    flip mid-chunk, and the engine's host-side schedule simulation computes
    the exact per-step flag.

    A token's write position AND its liveness travel WITH its hidden state
    (pos_state / live_state are ppermuted alongside x), because the ring
    always holds one in-flight token per slot.  The live flag is the single
    source of truth for realness: KV only commits for live tokens (idle-slot
    garbage touches nothing), and exit-side state writes (entry token, key
    burn, counts) are gated on the exiting token's flag — a stale token from
    a re-assigned or idle slot can neither corrupt the fresh prefill's KV
    rows nor clobber the injected entry token.  The engine kills the flag of
    a slot's in-flight token at injection time (it knows which rank holds
    it — see PipelinedMeshEngine.prefill_and_sample's stale-kill scan).

    Segmented models (ring_phases > 1) run each token through `phases` laps:
    a per-token phase travels with the hidden state the same way, entries
    only open on steps whose arriving token has finished its last lap, and
    the per-step schedule (entry_open / entry_slot / exit_valid / exit_slot)
    is precomputed host-side from the closed-form multi-lap schedule
    (_entry_open/_entry_slot) and consumed by the scan.

    Data parallelism shards SLOTS over dp lanes: `n_slots` is the PER-LANE
    slot count and every lane runs this same schedule over its own slots
    (global slot = lane * M + local), so capacity scales linearly with dp
    while the compiled schedule stays lane-invariant.  All per-slot state
    (tokens/pos_vec/keys/counts/sp_stack and the kv slot-batch axis) is
    dp-sharded lane-major; only `enter_live` is genuinely per-lane data
    (which slots carry real requests) and arrives [dp, n_steps].  Sampling
    runs per lane on its own slots — dp-varying by construction, which is
    why no identity psum over dp appears anywhere (r3's dp=1 pin).
    """
    PP = mesh.shape[AXIS_PP]
    M, B = n_slots, batch
    phases = getattr(model, "ring_phases", 1)
    PHI = phases * PP  # stage-steps a token occupies the ring
    n_steps = M * phases if n_steps is None else n_steps
    has_kinds = getattr(model, "layer_kinds", None) is not None
    # sequence parallelism: each sp rank holds a shard of every slot's KV
    # sequence axis; decode attention runs as distributed flash-decoding
    # (the same kv_spec/sp_axis plumbing as the sequential mesh ring)
    sp_axis = AXIS_SP if mesh.shape.get(AXIS_SP, 1) > 1 else None

    x_spec = P(AXIS_PP, AXIS_DP)  # x_state [PP, DP*B, 1, D]
    in_specs = (
        window_param_specs(window_params),
        P(),  # edge params replicated
        x_spec,
        kv_spec(sp_axis is not None),  # [L, DP*M*B, S(/sp), KVH, Hd]
        P(AXIS_DP),  # tokens [DP*M, B]
        P(AXIS_DP),  # pos_vec [DP*M]
        P(AXIS_PP, AXIS_DP),  # pos_state [PP, DP]
        P(AXIS_PP, AXIS_DP),  # live_state [PP, DP] bool
        P(AXIS_PP, AXIS_DP),  # phase_state [PP, DP] int32 (lap of in-flight token)
        P(),  # entry_open [n_steps] bool (schedule: step takes an entry)
        P(AXIS_DP),  # enter_live [DP, n_steps] bool (per-lane real-entry flag)
        P(),  # entry_slot [n_steps] int32 (lane-local slot)
        P(),  # exit_valid [n_steps] bool (schedule: step finishes a token)
        P(),  # exit_slot [n_steps] int32 (lane-local slot)
        P(AXIS_DP),  # sp_stack (SampleParams leaves [DP*M])
        P(AXIS_DP),  # keys [DP*M, 2] uint32
        P(AXIS_DP),  # counts [DP*M, B, V]
        P(),  # t0 scalar
        P(AXIS_PP) if has_kinds else P(),
    )
    res_spec = SampleResult(
        P(None, AXIS_DP), P(None, AXIS_DP), P(None, AXIS_DP), P(None, AXIS_DP)
    )  # leaves [n_steps, DP*B, ...]: every lane emits its own exit row
    out_specs = (
        res_spec, x_spec, kv_spec(sp_axis is not None), P(AXIS_DP), P(AXIS_DP),
        P(AXIS_PP, AXIS_DP), P(AXIS_PP, AXIS_DP), P(AXIS_PP, AXIS_DP),
        P(AXIS_DP), P(AXIS_DP),
    )

    def spmd(window_params, edge_params, x_state, kv, tokens, pos_vec,
             pos_state, live_state, phase_state, entry_open, enter_live,
             entry_slot, exit_valid, exit_slot, sp_stack, keys, counts,
             t0, kinds):
        my_pp = lax.axis_index(AXIS_PP)
        x = x_state[0]  # local [B, 1, D], device-varying over pp (and dp)
        pos_x = pos_state[0, 0]  # this (pp, lane) rank's in-flight position
        live_x = live_state[0, 0]  # is this rank's in-flight token real?
        phase_x = phase_state[0, 0]  # this rank's in-flight token lap
        live_row = enter_live[0]  # this lane's per-step real-entry flags

        def step(carry, j):
            x, pos_x, live_x, phase_x, kv, tokens, pos_vec, keys, counts = carry
            t = t0 + j
            open_j = lax.dynamic_index_in_dim(entry_open, j, keepdims=False)
            n = lax.dynamic_index_in_dim(entry_slot, j, keepdims=False)
            e = lax.dynamic_index_in_dim(exit_slot, j, keepdims=False)
            evalid_j = lax.dynamic_index_in_dim(exit_valid, j, keepdims=False)

            # entry: on schedule-open steps rank 0 replaces its (just-
            # drained) hidden with the entering token's embedding; the
            # token's position is consumed from pos_vec NOW and rides along
            # with the hidden thereafter.  On closed steps the arriving
            # token continues its next lap untouched.
            take = (my_pp == 0) & open_j
            tok_in = lax.dynamic_index_in_dim(tokens, n, keepdims=False)  # [B]
            x_embed = model.embed(edge_params, tok_in[:, None])
            # tokens are dp-sharded, so the embedding is already dp-varying;
            # only the pp axis needs the explicit cast
            x_embed = pcast_varying(x_embed, AXIS_PP)
            x_in = jnp.where(take, x_embed, x)
            pos_entry = lax.dynamic_index_in_dim(pos_vec, n, keepdims=False)
            pos_in = jnp.where(take, pos_entry, pos_x)
            live_entry = lax.dynamic_index_in_dim(live_row, j, keepdims=False)
            live_entry = pcast_varying(live_entry, AXIS_PP)
            live_in = jnp.where(take, live_entry, live_x)
            phase_in = jnp.where(take, 0, phase_x)
            pos_vec = lax.dynamic_update_index_in_dim(
                pos_vec, jnp.where(open_j, pos_entry + 1, pos_entry), n, axis=0
            )

            # this rank's slot follows from its token's entry step:
            # te = t - rank - PP*lap; slot = entry_slot(te) (closed form).
            # Garbage tokens (cold ring) may compute an arbitrary slot — they
            # never commit KV, so their reads/writes are inert.
            te = t - my_pp - PP * phase_in
            k_idx = (te // PHI) * PP + jnp.mod(te, PHI)
            my_slot = jnp.mod(k_idx, M)

            # this rank's stage over its slot's KV slice; only live tokens
            # commit KV (stale/idle garbage writes nothing, anywhere)
            kv_slot = jax.tree.map(
                lambda a: lax.dynamic_slice_in_dim(a, my_slot * B, B, axis=1), kv
            )
            extra = {"phase": phase_in} if phases > 1 else {}
            x_out, kv_slot = model.apply_window(
                window_params, x_in, kv_slot, pos_in,
                layer_kinds=kinds, tp_axis=AXIS_TP, kv_commit=live_in,
                sp_axis=sp_axis, **extra,
            )
            kv = jax.tree.map(
                lambda full, sl: lax.dynamic_update_slice_in_dim(
                    full, sl, my_slot * B, axis=1
                ),
                kv, kv_slot,
            )

            # exit: rank PP-1's x_out is the finished hidden of slot e
            x_last = model.normalize(edge_params, x_out)
            logits = model.lm_project(edge_params, x_last)[:, 0]  # [B, V]
            logits = _bcast_from_rank(logits, AXIS_PP, PP - 1)
            # no dp collective here: each lane samples its OWN slot's exit —
            # the sampling state (tokens/keys/counts) is dp-sharded, so
            # dp-varying logits are exactly right (r3's identity psum gone)

            # the exiting token's own live flag decides realness (bcast from
            # the last rank, where it resides this step); schedule steps that
            # finish no token (mid-lap arrivals) are never real
            real = (
                lax.psum(
                    jnp.where(my_pp == PP - 1, live_in.astype(jnp.int32), 0),
                    AXIS_PP,
                )
                > 0
            ) & evalid_j
            old_key = lax.dynamic_index_in_dim(keys, e, keepdims=False)
            key = jax.random.wrap_key_data(old_key)
            key, step_key = jax.random.split(key)
            sp_e = SampleParams(*(lax.dynamic_index_in_dim(a, e, keepdims=False)
                                  for a in sp_stack))
            counts_e = lax.dynamic_index_in_dim(counts, e, keepdims=False)
            res = sample(logits, sp_e, step_key, token_counts=counts_e)
            # stale exits (re-assigned slot, cold pipeline) must not touch
            # slot state: no key burn, no counts, no entry-token clobber
            counts_new = counts_e.at[jnp.arange(B), res.token].add(1)
            counts = lax.dynamic_update_index_in_dim(
                counts, jnp.where(real, counts_new, counts_e), e, axis=0
            )
            keys = lax.dynamic_update_index_in_dim(
                keys, jnp.where(real, jax.random.key_data(key), old_key), e, axis=0
            )
            tok_e = lax.dynamic_index_in_dim(tokens, e, keepdims=False)
            tokens = lax.dynamic_update_index_in_dim(
                tokens, jnp.where(real, res.token, tok_e), e, axis=0
            )

            # hand hidden states (and their position/liveness/lap) one hop
            # around; crossing the PP-1 -> 0 seam advances the lap counter
            perm = [(p, (p + 1) % PP) for p in range(PP)]
            x_next = lax.ppermute(x_out, AXIS_PP, perm)
            pos_next = lax.ppermute(pos_in, AXIS_PP, perm)
            live_next = lax.ppermute(live_in, AXIS_PP, perm)
            phase_next = lax.ppermute(
                phase_in + (my_pp == PP - 1).astype(jnp.int32), AXIS_PP, perm
            )
            return (x_next, pos_next, live_next, phase_next, kv, tokens,
                    pos_vec, keys, counts), res

        (x, pos_x, live_x, phase_x, kv, tokens, pos_vec, keys, counts), results = (
            lax.scan(
                step,
                (x, pos_x, live_x, phase_x, kv, tokens, pos_vec, keys, counts),
                jnp.arange(n_steps, dtype=jnp.int32),
            )
        )
        return (results, x[None], kv, tokens, pos_vec, pos_x[None, None],
                live_x[None, None], phase_x[None, None], keys, counts)

    fn = shard_map(spmd, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    jitted = jax.jit(fn, donate_argnums=(2, 3, 4, 5, 6, 7, 8, 15, 16))
    kinds_arr = (
        model.layer_kinds if has_kinds else jnp.zeros((), dtype=jnp.int32)
    )

    def call(window_params, edge_params, x_state, kv, tokens, pos_vec,
             pos_state, live_state, phase_state, entry_open, enter_live,
             entry_slot, exit_valid, exit_slot, sp_stack, keys, counts, t0):
        return jitted(window_params, edge_params, x_state, kv, tokens, pos_vec,
                      pos_state, live_state, phase_state, entry_open,
                      enter_live, entry_slot, exit_valid, exit_slot, sp_stack,
                      keys, counts, jnp.int32(t0), kinds_arr)

    return call


def make_slot_prefill_fn(model, mesh: Mesh, window_params, n_slots: int, batch: int = 1):
    """Sequential ring pass (parallel/ring.py schedule) writing ONE slot's KV.

    (window_params, edge_params, tokens[B,T], kv, pos, last_idx, slot, lane)
      -> (logits[B,V], kv)

    `slot` is lane-local; `lane` selects the dp lane that owns the request —
    every lane traces the same pass (SPMD), but only the owning lane's
    kv_commit fires and only its logits survive the dp broadcast.
    """
    PP = mesh.shape[AXIS_PP]
    B = batch
    phases = getattr(model, "ring_phases", 1)
    has_kinds = getattr(model, "layer_kinds", None) is not None
    sp_axis = AXIS_SP if mesh.shape.get(AXIS_SP, 1) > 1 else None
    in_specs = (
        window_param_specs(window_params),
        P(),
        P(),  # tokens [B, T] replicated: every lane traces the same pass
        kv_spec(sp_axis is not None), P(), P(), P(), P(),
        P(AXIS_PP) if has_kinds else P(),
    )
    out_specs = (P(), kv_spec(sp_axis is not None))

    def spmd(window_params, edge_params, tokens, kv, pos, last_idx, slot, lane,
             kinds):
        my_pp = lax.axis_index(AXIS_PP)
        mine = lax.axis_index(AXIS_DP) == lane
        kv_slot = jax.tree.map(
            lambda a: lax.dynamic_slice_in_dim(a, slot * B, B, axis=1), kv
        )
        x = model.embed(edge_params, tokens)
        x = pcast_varying(x, AXIS_PP)
        x = pcast_varying(x, AXIS_DP)

        def stage_iter(i, carry):
            x, kv_slot = carry
            # segmented models take `phases` laps (lap p applies every
            # rank's slice of segment p — parallel/ring.py's schedule)
            extra = {"phase": i // PP} if phases > 1 else {}
            x_new, kv_slot = model.apply_window(
                window_params, x, kv_slot, pos,
                layer_kinds=kinds, tp_axis=AXIS_TP,
                kv_commit=(jnp.mod(i, PP) == my_pp) & mine,
                sp_axis=sp_axis, t_real=last_idx + 1, **extra,
            )
            x_next = lax.ppermute(
                x_new, AXIS_PP, [(p, (p + 1) % PP) for p in range(PP)]
            )
            return (x_next, kv_slot)

        x, kv_slot = lax.fori_loop(0, phases * PP, stage_iter, (x, kv_slot))
        kv = jax.tree.map(
            lambda full, sl: lax.dynamic_update_slice_in_dim(
                full, sl, slot * B, axis=1
            ),
            kv, kv_slot,
        )
        x_last = lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)
        x_last = model.normalize(edge_params, x_last)
        logits = model.lm_project(edge_params, x_last)
        logits = _bcast_from_rank(logits, AXIS_PP, 0)
        # keep the owning lane's logits and replicate (bcast, not identity)
        logits = lax.psum(jnp.where(mine, logits, jnp.zeros_like(logits)), AXIS_DP)
        return logits[:, 0], kv

    fn = shard_map(spmd, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    jitted = jax.jit(fn, donate_argnums=(3,))
    kinds_arr = (
        model.layer_kinds if has_kinds else jnp.zeros((), dtype=jnp.int32)
    )

    def call(window_params, edge_params, tokens, kv, pos, last_idx, slot, lane=0):
        return jitted(window_params, edge_params, tokens, kv, jnp.int32(pos),
                      jnp.int32(last_idx), jnp.int32(slot), jnp.int32(lane),
                      kinds_arr)

    return call


class PipelinedMeshEngine:
    """BatchedEngine-compatible surface over the rotation program.

    M slots serve up to M concurrent requests; `decode_batch` runs rotations
    until every pending request has a result (steady state: exactly one).
    Drop-in behind BatchedLocalAdapter — continuous batching ACROSS the
    pipeline, the scheduler the sequential mesh ring lacks
    (VERDICT.md "MeshEngine pipeline is (PP-1)/PP idle").
    """

    def __init__(
        self,
        model_dir,
        pp: int = 0,
        tp: int = 1,
        sp: int = 1,
        dp: int = 1,
        slots: int = 0,
        max_seq: int = 2048,
        param_dtype: str = "bfloat16",
        kv_dtype: Optional[str] = None,
        kv_quant_bits: int = 0,
        weight_quant_bits: int = 0,
        quant_group: int = 0,
        devices: Optional[Sequence] = None,
        prefix_cache_size: int = 0,
    ):
        import numpy as np

        from dnet_tpu.parallel.engine import MeshEngine

        # resolve pp before sizing the slot pool (shared helper: the serving
        # manager's precheck must agree with this engine's resolution)
        if pp <= 0:
            import json
            from pathlib import Path as _Path

            n_dev = len(list(devices) if devices is not None else jax.devices())
            L = json.loads(
                (_Path(model_dir) / "config.json").read_text()
            )["num_hidden_layers"]
            pp = resolve_pp(n_dev, tp * dp, sp, L)
        # dp shards SLOTS: dp lanes each run the same per-lane schedule over
        # M_local slots (global slot = lane * M_local + local) — capacity
        # scales linearly, the schedule stays lane-invariant
        self.dp = dp = max(dp, 1)
        self.n_slots = M = slots if slots > 0 else pp * dp
        if M % dp != 0:
            raise ValueError(f"slots={M} must be divisible by dp={dp}")
        self.m_local = M_local = M // dp
        if M_local < pp:
            raise ValueError(
                f"slots={M} gives {M_local} per dp lane; need >= pp={pp} "
                f"to fill the pipeline"
            )
        self.slot_batch = B = 1
        # the inner MeshEngine loads/shards params and builds the kv template
        # with batch = dp*M_local*B (lanes x slots folded into the batch axis,
        # lane-major so the dp sharding blocks align with global slot ids)
        self._inner = MeshEngine(
            model_dir, pp=pp, tp=tp, dp=dp, sp=sp, batch=M_local * B,
            max_seq=max_seq,
            param_dtype=param_dtype, kv_dtype=kv_dtype,
            kv_quant_bits=kv_quant_bits, weight_quant_bits=weight_quant_bits,
            quant_group=quant_group, devices=devices,
        )
        inner = self._inner
        if not inner.model.supports_kv_commit:
            raise NotImplementedError(
                f"pipelined serving not supported for "
                f"{inner.config.model_type} (no gated KV writes yet)"
            )
        self.config, self.model, self.mesh = inner.config, inner.model, inner.mesh
        self.pp, self.tp, self.sp = inner.pp, inner.tp, inner.sp
        # segmented models (deepseek ring_phases=2) take `phases` laps per
        # token: one rotation is M*phases stage-steps and still yields one
        # entry + one exit per slot (the multi-lap schedule's entry bursts
        # cycle the slots round-robin — see _entry_open/_entry_slot)
        self.phases = getattr(inner.model, "ring_phases", 1)
        self.max_seq = max_seq
        self.window_params, self.edge_params = inner.window_params, inner.edge_params

        # rotation programs cached per fused-rotation count R (R*M_local
        # stage steps per dispatch); R=1 built eagerly, larger on demand
        self._host_window_ref = inner._host_window
        self._rot_fns = {
            1: make_rotation_fn(
                self.model, self.mesh, inner._host_window, M_local, B
            )
        }
        self._prefill_fn = make_slot_prefill_fn(
            self.model, self.mesh, inner._host_window, M_local, B
        )

        from jax.sharding import NamedSharding

        D = self.config.hidden_size
        V = self.config.vocab_size
        lane_sh = NamedSharding(self.mesh, P(AXIS_DP))  # slot-major over lanes
        self.x_state = jax.device_put(
            jnp.zeros((self.pp, dp * B, 1, D), dtype=jnp.dtype(param_dtype)),
            NamedSharding(self.mesh, P(AXIS_PP, AXIS_DP)),
        )
        self.kv = inner._kv_template  # [L, dp*M_local*B, S, ...] mesh-sharded
        self.tokens = jax.device_put(jnp.zeros((M, B), dtype=jnp.int32), lane_sh)
        self.pos_vec = jax.device_put(jnp.zeros((M,), dtype=jnp.int32), lane_sh)
        pp_dp = NamedSharding(self.mesh, P(AXIS_PP, AXIS_DP))
        self.pos_state = jax.device_put(
            jnp.zeros((self.pp, dp), dtype=jnp.int32), pp_dp
        )
        self.live_state = jax.device_put(
            jnp.zeros((self.pp, dp), dtype=bool), pp_dp
        )
        self.phase_state = jax.device_put(
            jnp.zeros((self.pp, dp), dtype=jnp.int32), pp_dp
        )
        self.keys = jax.device_put(jnp.zeros((M, 2), dtype=jnp.uint32), lane_sh)
        self.counts = jax.device_put(
            jnp.zeros((M, B, V), dtype=jnp.int32), lane_sh
        )
        self.t0 = 0

        self.slot_of: Dict[str, int] = {}
        self._free = list(range(M))
        self.slot_pos = np.zeros(M, dtype=np.int64)  # host mirror of pos_vec
        self._dec: Dict[int, "DecodingParams"] = {}  # slot -> sampling params
        self._entries: Dict[int, list] = {i: [] for i in range(M)}  # entry steps
        self._buffer: Dict[str, list] = {}  # nonce -> ready SampleResults
        self._last_used: Dict[str, float] = {}  # nonce -> wall time (TTL sweep)
        self.prefix_cache = None
        if prefix_cache_size > 0:
            # snapshots are SLOT-ROW slices of the shared cache ([L, B, S,
            # ...], mesh-sharded): restore writes the rows back into
            # whichever slot the new request lands on
            from dnet_tpu.core.prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(prefix_cache_size)
        # dispatched-but-unread rotation chunks: (deliveries [(j, nonce)],
        # stacked SampleResult device arrays) — reads drain in dispatch order,
        # overlapping the next chunk's compute
        self._pending_rot: list = []
        self._np = np

    token_result = None  # set after class body (LocalEngine staticmethod)

    @property
    def sessions(self):
        return self.slot_of

    # ---- slots --------------------------------------------------------
    def _alloc(self, nonce: str) -> int:
        if nonce in self.slot_of:
            return self.slot_of[nonce]
        if not self._free:
            raise RuntimeError(f"no free pipeline slots (capacity {self.n_slots})")
        slot = self._free.pop(0)
        self.slot_of[nonce] = slot
        self._entries[slot] = []
        self._buffer[nonce] = []
        self._last_used[nonce] = time.time()
        return slot

    def end_session(self, nonce: str) -> None:
        slot = self.slot_of.pop(nonce, None)
        self._buffer.pop(nonce, None)
        self._last_used.pop(nonce, None)
        if slot is not None:
            self._dec.pop(slot, None)
            self._entries[slot] = []
            self._free.append(slot)

    def reset(self) -> None:
        for nonce in list(self.slot_of):
            self.end_session(nonce)

    def close(self) -> None:
        self.reset()

    def sweep_sessions(self, ttl_s: float = 600.0) -> int:
        """Free slots whose nonce has been idle past the TTL — a client that
        disconnected without adapter cleanup must not pin a slot forever
        (at capacity, _alloc fails for every new request)."""
        now = time.time()
        dead = [
            n for n, t in self._last_used.items()
            if now - t > ttl_s and n in self.slot_of
        ]
        for n in dead:
            self.end_session(n)
        return len(dead)

    # ---- serving ------------------------------------------------------
    def prefill_and_sample(self, nonce, prompt_ids, decoding) -> SampleResult:
        from dnet_tpu.core.engine import bucket_length
        from dnet_tpu.core.types import DecodingParams  # noqa: F401

        np = self._np
        full_ids = list(prompt_ids)
        T_total = len(full_ids)
        if T_total == 0:
            raise ValueError("empty prompt")
        if T_total >= self.max_seq:
            raise ValueError(
                f"prompt length {T_total} exceeds max_seq {self.max_seq}"
            )
        slot = self._alloc(nonce)
        B = self.slot_batch
        base, rest = 0, full_ids
        if self.prefix_cache is not None:
            hit = self.prefix_cache.lookup(full_ids)
            if hit is not None:
                base, kv_row = hit  # >= 1 token left by construction
                self.kv = jax.tree.map(
                    lambda big, row: big.at[:, slot * B : (slot + 1) * B].set(
                        row.astype(big.dtype)
                    ),
                    self.kv, kv_row,
                )
                rest = full_ids[base:]
        T = len(rest)
        Tpad = min(bucket_length(T), self.max_seq - base)
        tokens = np.zeros((B, Tpad), dtype=np.int32)
        tokens[:, :T] = np.asarray(rest, dtype=np.int32)
        lane, local = divmod(slot, self.m_local)
        logits, self.kv = self._prefill_fn(
            self.window_params, self.edge_params, jnp.asarray(tokens),
            self.kv, base, T - 1, local, lane,
        )
        if self.prefix_cache is not None:
            self.prefix_cache.store(
                full_ids,
                jax.tree.map(
                    lambda a: jax.lax.dynamic_slice_in_dim(a, slot * B, B, axis=1),
                    self.kv,
                ),
            )
        seed = decoding.seed
        if seed is None:
            seed = int.from_bytes(__import__("os").urandom(4), "little")
        key = jax.random.key(seed)
        key, step_key = jax.random.split(key)
        counts0 = jnp.zeros((B, self.config.vocab_size), dtype=jnp.int32)
        res = sample(
            logits, SampleParams.from_decoding(decoding), step_key,
            token_counts=counts0,
        )
        counts0 = counts0.at[jnp.arange(B), res.token].add(1)
        # inject: the sampled token is this slot's first pipeline entry
        self.tokens = self.tokens.at[slot].set(res.token)
        self.pos_vec = self.pos_vec.at[slot].set(T_total)
        self.keys = self.keys.at[slot].set(jax.random.key_data(key))
        self.counts = self.counts.at[slot].set(counts0)
        # kill the slot's stale in-flight token: between rotations, rank r
        # carries the token that entered at te = t0 - r - PP*lap (exactly one
        # lap makes te an entry-open step) — its live flag must not let old
        # garbage commit KV into the rows this prefill just wrote.  The
        # schedule is lane-local, so the match is against the LOCAL slot and
        # the kill lands on this lane's column of live_state.
        for r in range(self.pp):
            for p in range(self.phases):
                te = self.t0 - r - self.pp * p
                if (
                    te >= 0
                    and _entry_open(te, self.pp, self.phases)
                    and _entry_slot(te, self.pp, self.phases, self.m_local) == local
                ):
                    self.live_state = self.live_state.at[r, lane].set(False)
        self.slot_pos[slot] = T_total
        self._dec[slot] = decoding
        return res

    def _sp_stack(self) -> SampleParams:
        np = self._np
        M = self.n_slots
        temp = np.zeros(M, dtype=np.float32)
        top_p = np.ones(M, dtype=np.float32)
        top_k = np.zeros(M, dtype=np.int32)
        min_p = np.zeros(M, dtype=np.float32)
        rep = np.ones(M, dtype=np.float32)
        mtk = np.ones(M, dtype=np.int32)
        b_ids = np.full((M, MAX_LOGIT_BIAS), -1, dtype=np.int32)
        b_vals = np.zeros((M, MAX_LOGIT_BIAS), dtype=np.float32)
        for slot, dec in self._dec.items():
            temp[slot] = dec.temperature
            top_p[slot] = dec.top_p
            top_k[slot] = dec.top_k
            min_p[slot] = dec.min_p
            rep[slot] = dec.repetition_penalty
            mtk[slot] = dec.min_tokens_to_keep
            b_ids[slot], b_vals[slot] = encode_logit_bias(dec.logit_bias)
        return SampleParams(
            jnp.asarray(temp), jnp.asarray(top_p), jnp.asarray(top_k),
            jnp.asarray(min_p), jnp.asarray(rep), jnp.asarray(mtk),
            jnp.asarray(b_ids), jnp.asarray(b_vals),
        )

    # fused-rotation widths tried largest-first (one compiled program per
    # width actually used, same bounded-bucket discipline as
    # LocalEngine.DECODE_CHUNK_BUCKETS)
    ROTATION_BUCKETS = (8, 4, 2, 1)

    def _rot_fn(self, R: int):
        fn = self._rot_fns.get(R)
        if fn is None:
            fn = make_rotation_fn(
                self.model, self.mesh, self._host_window_ref,
                self.m_local, self.slot_batch,
                n_steps=R * self.m_local * self.phases,
            )
            self._rot_fns[R] = fn
        return fn

    def _dispatch_chunk(self, R: int) -> None:
        """Dispatch (async) R fused rotations: R*M*phases stage-steps, one
        XLA program, sampled tokens re-entering their slots on device.  The
        delivery schedule (which exit step belongs to which nonce) is
        simulated host-side at dispatch time — it depends only on the entry
        bookkeeping, never on token VALUES, so the packed results can be
        read later (overlapping the next chunk's compute)."""
        np = self._np
        M_local, PP, phases, DP = self.m_local, self.pp, self.phases, self.dp
        PHI = phases * PP
        nonce_of = {s: n for n, s in self.slot_of.items()}
        sim = {m: list(self._entries[m]) for m in range(self.n_slots)}
        pos_sim = self.slot_pos.copy()
        deliveries = []  # (step index j, lane, nonce at dispatch time)
        n_steps = R * M_local * phases
        entry_open = np.zeros(n_steps, dtype=bool)
        enter_live = np.zeros((DP, n_steps), dtype=bool)
        entry_slot = np.zeros(n_steps, dtype=np.int32)
        exit_valid = np.zeros(n_steps, dtype=bool)
        exit_slot = np.zeros(n_steps, dtype=np.int32)
        for j in range(n_steps):
            t = self.t0 + j
            te = t - (PHI - 1)  # exit latency: phases laps of PP hops
            if te >= 0 and _entry_open(te, PP, phases):
                e_local = _entry_slot(te, PP, phases, M_local)
                exit_valid[j] = True
                exit_slot[j] = e_local
                # every dp lane exits its own slot at this step
                for lane in range(DP):
                    g = lane * M_local + e_local
                    ent = sim[g]
                    if ent and ent[0] == te:
                        ent.pop(0)
                        if g in nonce_of:
                            deliveries.append((j, lane, nonce_of[g]))
            if _entry_open(t, PP, phases):
                n_local = _entry_slot(t, PP, phases, M_local)
                entry_open[j] = True
                entry_slot[j] = n_local
                # a live slot below capacity feeds one real token this step;
                # lane d's device consumes enter_live[d, j] in its scan
                for lane in range(DP):
                    g = lane * M_local + n_local
                    if g in nonce_of and pos_sim[g] < self.max_seq:
                        enter_live[lane, j] = True
                        sim[g].append(t)
                    # pos_vec advances unconditionally at the entry step
                    # (device mirrors this); gated KV commits make
                    # dead-slot writes inert
                    pos_sim[g] += 1
        (results, self.x_state, self.kv, self.tokens, self.pos_vec,
         self.pos_state, self.live_state, self.phase_state, self.keys,
         self.counts) = self._rot_fn(R)(
            self.window_params, self.edge_params, self.x_state, self.kv,
            self.tokens, self.pos_vec, self.pos_state, self.live_state,
            self.phase_state, jnp.asarray(entry_open), jnp.asarray(enter_live),
            jnp.asarray(entry_slot), jnp.asarray(exit_valid),
            jnp.asarray(exit_slot), self._sp_stack(), self.keys, self.counts,
            self.t0,
        )
        self._pending_rot.append((deliveries, results))
        self._entries = sim
        # pos_sim IS the device pos_vec mirror; for phases>1 with
        # n_slots % pp != 0 the entry bursts do NOT distribute exactly R
        # entries per slot per chunk, so a blanket += R would desync
        self.slot_pos = pos_sim
        self.t0 += n_steps

    def _drain_pending(self) -> None:
        """Read every dispatched-but-unread chunk (ONE packed device->host
        transfer per chunk) and route tokens to their nonce buffers.  A
        nonce that ended between dispatch and drain has no buffer entry —
        its tokens are dropped, exactly like LocalAdapter's aborted-chunk
        leftovers."""
        np = self._np
        B = self.slot_batch
        while self._pending_rot:
            deliveries, results = self._pending_rot.pop(0)
            toks = np.asarray(results.token)  # [n_steps, DP*B]
            lps = np.asarray(results.logprob)
            tts = np.asarray(results.top_tokens)
            tlps = np.asarray(results.top_logprobs)
            for j, lane, nonce in deliveries:
                if nonce in self._buffer:
                    sl = slice(lane * B, (lane + 1) * B)
                    self._buffer[nonce].append(
                        SampleResult(toks[j, sl], lps[j, sl], tts[j, sl], tlps[j, sl])
                    )

    def decode_batch(
        self, requests, budgets: Optional[Dict[str, Optional[int]]] = None
    ) -> Tuple[Dict[str, SampleResult], Dict[str, str]]:
        """One result per requested nonce; `budgets` (nonce -> remaining
        tokens the driver will accept, None = unknown) widens the dispatch:
        R fused rotations produce R tokens per slot in one program, the
        extras resolving later decode_batch calls instantly from the
        buffers.  Without budgets the behavior is the r2 one-rotation step.
        """
        errors: Dict[str, str] = {}
        order: Dict[str, int] = {}
        for nonce, (_tok, dec) in requests.items():
            slot = self.slot_of.get(nonce)
            if slot is None:
                errors[nonce] = f"request {nonce!r} has no pipeline slot (cancelled?)"
                continue
            self._dec[slot] = dec
            order[nonce] = slot
            self._last_used[nonce] = time.time()
        if not order:
            return {}, errors

        def can_progress(nonce: str) -> bool:
            """More tokens can still arrive: capacity to enter, in flight,
            or dispatched-but-unread."""
            slot = order[nonce]
            return (
                self.slot_pos[slot] < self.max_seq
                or bool(self._entries[slot])
                or bool(self._pending_rot)
            )

        def pick_R(missing) -> int:
            """Largest fused-rotation width no request would overshoot:
            bounded by the smallest remaining budget MINUS that nonce's
            in-flight ring entries (each will deliver a token before any new
            entry from this chunk does) and by seq capacity."""
            if not budgets:
                return 1
            cap = min(
                max((budgets.get(n) or 1) - len(self._entries[order[n]]), 1)
                for n in missing
            )
            cap = min(cap, *(int(self.max_seq - self.slot_pos[order[n]])
                             for n in missing))
            return next((b for b in self.ROTATION_BUCKETS if b <= cap), 1)

        # steady state: one rotation yields one token per active slot; a
        # freshly prefilled slot needs a second (its first entry is mid-ring)
        for _ in range(3):
            self._drain_pending()
            missing = [n for n in order if not self._buffer.get(n)]
            if not missing or not any(can_progress(n) for n in missing):
                break
            self._dispatch_chunk(pick_R(missing))
        self._drain_pending()
        out: Dict[str, SampleResult] = {}
        for nonce, slot in order.items():
            buf = self._buffer.get(nonce)
            if buf:
                # buffered tokens generated before capacity are still valid
                out[nonce] = buf.pop(0)
            elif self.slot_pos[slot] >= self.max_seq:
                errors[nonce] = (
                    f"sequence length {self.slot_pos[slot]} reached max_seq "
                    f"{self.max_seq}"
                )
                self.end_session(nonce)
            else:
                errors[nonce] = "pipeline produced no token (stall)"
        return out, errors

    def generate(self, prompt_ids, decoding=None, max_tokens=256,
                 eos_token_ids=None, nonce="pipelined"):
        from dnet_tpu.core.types import DecodingParams

        decoding = decoding or DecodingParams()
        eos = eos_token_ids or set()
        self.end_session(nonce)
        res = self.prefill_and_sample(nonce, prompt_ids, decoding)
        token = int(res.token[0])
        yield self.token_result(nonce, res, step=0, decoding=decoding)
        if token in eos:
            self.end_session(nonce)
            return
        for step in range(1, max_tokens):
            if self.slot_pos[self.slot_of[nonce]] >= self.max_seq:
                break
            res_map, errs = self.decode_batch(
                {nonce: (token, decoding)},
                budgets={nonce: max_tokens - step},
            )
            if errs:
                raise RuntimeError(errs[nonce])
            row = res_map[nonce]
            token = int(row.token[0])
            yield self.token_result(nonce, row, step=step, decoding=decoding)
            if token in eos:
                break
        self.end_session(nonce)


def _bind_token_result():
    from dnet_tpu.core.engine import LocalEngine

    PipelinedMeshEngine.token_result = staticmethod(LocalEngine.token_result)


_bind_token_result()
