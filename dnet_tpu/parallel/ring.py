"""In-slice pipelined-ring execution: the whole ring in ONE XLA program.

The reference moves activations shard-to-shard with gRPC frames
(src/dnet/shard/adapters/ring.py:241-299).  When the "shards" are chips of
one TPU slice, the entire per-token pipeline compiles into a single
shard_map program: each pp-rank applies its contiguous stage of layers, and
the hidden state hops to the next rank with `lax.ppermute` over ICI — no
serialization, no host round-trips.  Tensor parallelism nests inside each
stage (psum seams in the model), data parallelism replicates the whole ring.

Pipelining model: for a single in-flight token the ring runs PP sequential
stage-steps (other ranks compute garbage that is masked out of KV); with S
concurrent sequences the same program reaches steady state where every rank
does real work every step (classic pipelined-ring round-robin, the analog of
the reference's k-round schedule, src/dnet/api/utils.py:62-131).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dnet_tpu.utils.jax_compat import pcast_varying, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dnet_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    kv_spec,
    window_param_specs,
)


def _ring_spmd(model, mesh: Mesh, window_params, full_logits: bool = False,
               hidden_out: bool = False):
    """Construct the shard_map'd single-token ring step (un-jitted) and its
    layer-kinds operand.  Shared by the per-step fn (make_ring_decode_fn),
    the chunked-scan fn (make_ring_chunk_fn), the speculative verify fn
    (make_ring_spec_fn, full_logits=True: every position projected), and
    the embeddings fn (make_ring_hidden_fn, hidden_out=True: final-norm'd
    hidden states instead of the lm projection)."""
    PP = mesh.shape[AXIS_PP]
    phases = getattr(model, "ring_phases", 1)
    # sequence parallelism: KV shards over sp; queries/hidden replicate and
    # attention runs as ring/flash-decoding with one LSE combine per layer
    sp_axis = AXIS_SP if mesh.shape.get(AXIS_SP, 1) > 1 else None

    # mixed-attention models (gpt_oss) carry a per-layer kind array that must
    # shard over pp alongside the layer-stacked params
    has_kinds = getattr(model, "layer_kinds", None) is not None
    in_specs = (
        window_param_specs(window_params),
        P(),  # edge params replicated
        P(AXIS_DP, None),  # tokens [B, T]
        kv_spec(sp_axis is not None),  # pytree prefix: every kv leaf (incl. scales)
        P(),  # pos scalar
        P(),  # last_idx scalar
        P(AXIS_PP) if has_kinds else P(),
    )
    logits_spec = (
        P(AXIS_DP, None, None) if (full_logits or hidden_out) else P(AXIS_DP, None)
    )
    out_specs = (logits_spec, kv_spec(sp_axis is not None))

    def spmd(window_params, edge_params, tokens, kv, pos, last_idx, kinds):
        my_pp = lax.axis_index(AXIS_PP)

        # Stage 0 embeds; everyone runs the embed (cheap) but only rank 0's
        # x is "real" at iteration 0.
        x = model.embed(edge_params, tokens)
        # x becomes device-varying over pp once layer-sharded params touch
        # it (over tp it stays value-invariant thanks to the psum seams);
        # mark the loop carry so the carry types line up.
        x = pcast_varying(x, AXIS_PP)

        def stage_iter(i, carry):
            x, kv = carry
            # KV only commits on the rank whose turn it is (garbage copies
            # on other ranks must not pollute their caches); the gate is
            # O(T) inside the layer, not an O(S) whole-cache select.
            extra = {"phase": i // PP} if phases > 1 else {}
            x_new, kv = model.apply_window(
                window_params, x, kv, pos,
                layer_kinds=kinds, tp_axis=AXIS_TP,
                kv_commit=(jnp.mod(i, PP) == my_pp),
                sp_axis=sp_axis, t_real=last_idx + 1, **extra,
            )
            # hand the hidden state to the next pipeline rank (ICI hop)
            x_next = lax.ppermute(
                x_new, AXIS_PP, [(p, (p + 1) % PP) for p in range(PP)]
            )
            return (x_next, kv)

        x, kv = lax.fori_loop(0, phases * PP, stage_iter, (x, kv))
        # after PP hops the processed x is back on rank 0; ranks agree via
        # the ppermute ring, and rank 0 holds the final hidden state.
        if hidden_out:
            # embeddings path: every position's final-norm'd hidden state
            xs = model.normalize(edge_params, x)
            return _bcast_from_rank0(xs, AXIS_PP), kv
        if full_logits:
            # spec verify needs every position's argmax; T is tiny (L+1)
            xs = model.normalize(edge_params, x)
            logits = model.lm_project(edge_params, xs)  # [B, T, V]
            return _bcast_from_rank0(logits, AXIS_PP), kv
        x_last = lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)
        x_last = model.normalize(edge_params, x_last)
        logits = model.lm_project(edge_params, x_last)
        # Replicate rank 0's logits across pp (out_specs say logits are not
        # sharded over pp; only rank 0 holds the real value after the loop).
        logits = _bcast_from_rank0(logits, AXIS_PP)
        return logits[:, 0], kv

    fn = shard_map(spmd, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    kinds_arr = model.layer_kinds if has_kinds else jnp.zeros((), dtype=jnp.int32)
    return fn, kinds_arr


def make_ring_decode_fn(model, mesh: Mesh, window_params, donate_kv: bool = True):
    """Build a jitted single-program ring decode step.

    Signature of the returned fn:
      (window_params, edge_params, tokens[B,1] int32, kv, pos) -> (logits[B,V], kv)

    window_params: stacked over ALL model layers [L, ...], sharded
      (pp shards the layer axis into contiguous stages, tp the head/ffn dims)
      — passed here only for spec construction (flat or segmented layout).

    Models with `ring_phases > 1` (deepseek: dense/moe segments) run that
    many laps around the ring, applying one segment per lap, so the global
    layer order is preserved even though each rank holds a slice of every
    segment.
    """
    fn, kinds_arr = _ring_spmd(model, mesh, window_params)
    donate = (3,) if donate_kv else ()
    jitted = jax.jit(fn, donate_argnums=donate)

    def call(window_params, edge_params, tokens, kv, pos, last_idx=None):
        if last_idx is None:
            last_idx = jnp.int32(tokens.shape[1] - 1)
        return jitted(window_params, edge_params, tokens, kv, pos, last_idx, kinds_arr)

    return call


def make_ring_chunk_fn(model, mesh: Mesh, window_params):
    """Chunked-scan mesh decode: K ring steps + on-device sampling fused
    into ONE XLA program (the multi-chip analog of LocalEngine's
    decode_chunk, core/engine.py — same packed-result, device-chained-token
    contract, so LocalEngine's dispatch/read methods drive it unchanged).

    Per-token the served mesh path previously paid one full program dispatch
    + one host read (parallel/engine.py r2, the dispatch gap VERDICT flagged);
    here the sampled token feeds the next ring step on-device and the host
    pays one dispatch + one packed transfer per K tokens.  Sampling sits
    OUTSIDE shard_map at the global-batch level, so key evolution and noise
    shapes match the per-step path exactly (chunked and unchunked streams
    are identical for a given seed)."""
    from dnet_tpu.core.sampler import pack_chunk_results, sample

    ring, kinds_arr = _ring_spmd(model, mesh, window_params)

    def chunk(window_params, edge_params, token, kv, pos, sp, key, counts,
              n_steps, plan=None):
        def body(carry, _):
            tok, kv, pos, key, counts = carry
            key, step_key = jax.random.split(key)
            logits, kv = ring(
                window_params, edge_params, tok, kv, pos, jnp.int32(0), kinds_arr
            )
            res = sample(logits, sp, step_key, token_counts=counts, plan=plan)
            counts = counts.at[jnp.arange(counts.shape[0]), res.token].add(1)
            return (res.token[:, None], kv, pos + 1, key, counts), res

        (last_tok, kv, _, key, counts), results = jax.lax.scan(
            body, (token, kv, pos, key, counts), None, length=n_steps
        )
        packed = pack_chunk_results(results, plan is None or plan.logprobs)
        return packed, last_tok, kv, key, counts

    return jax.jit(chunk, static_argnums=(8, 9), donate_argnums=(3, 7))


def make_ring_spec_fn(model, mesh: Mesh, window_params, lookahead: int):
    """Speculative verify block through the mesh ring: draft `lookahead`
    tokens by prompt-lookup, run ONE ring pass over the [tok, drafts]
    block (L+1 positions instead of 1 — the extra positions ride the same
    PP stage-steps and ICI hops), greedily accept the agreeing prefix.

    Keeps LocalEngine's `_spec_step` contract
    ((wp, ep, tok, hist, kv, pos) -> (out, hist, kv), out[:, i] == -1
    beyond the accepted prefix), so LocalEngine.decode_spec and the
    serving adapter's spec path drive the mesh engine unchanged.
    Drafting/acceptance run at the global-batch level outside shard_map,
    exactly like chunked sampling (make_ring_chunk_fn)."""
    from dnet_tpu.core.spec import accept_drafts, commit_history, ngram_draft

    ring_full, kinds_arr = _ring_spmd(model, mesh, window_params, full_logits=True)
    L = int(lookahead)

    def spec_step(window_params, edge_params, tok, hist, kv, pos):
        hist = commit_history(hist, pos, tok, jnp.int32(1))
        drafts = ngram_draft(hist, pos + 1, L)  # [B, L]
        hist = commit_history(hist, pos + 1, drafts, jnp.int32(L))
        block = jnp.concatenate([tok, drafts], axis=1)  # [B, L+1]
        logits, kv = ring_full(
            window_params, edge_params, block, kv, pos, jnp.int32(L), kinds_arr
        )
        preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        _, out = accept_drafts(preds, drafts)
        return out, hist, kv

    return jax.jit(spec_step, donate_argnums=(3, 4))


def make_ring_hidden_fn(model, mesh: Mesh, window_params):
    """One ring pass returning final-norm'd hidden states [B, T, D] —
    the embeddings primitive for mesh-served models (the twin of
    LocalEngine.hidden_states).  KV is a throwaway: not donated, caller
    discards it."""
    fn, kinds_arr = _ring_spmd(model, mesh, window_params, hidden_out=True)
    jitted = jax.jit(fn)

    def call(window_params, edge_params, tokens, kv, pos, last_idx):
        return jitted(
            window_params, edge_params, tokens, kv, pos, last_idx, kinds_arr
        )

    return call


def _bcast_from_rank0(x, axis_name: str):
    """Replicate rank 0's value across the axis (psum of masked value)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == 0, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def place_ring_state(window_params, edge_params, kv, mesh: Mesh):
    """Device_put params/caches with ring shardings (host -> mesh)."""
    from dnet_tpu.parallel.mesh import replicate, shard_window_params

    sp = mesh.shape[AXIS_SP] > 1
    wp = shard_window_params(window_params, mesh)
    ep = replicate(edge_params, mesh)
    kvp = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, kv_spec(sp))), kv
    )
    return wp, ep, kvp
