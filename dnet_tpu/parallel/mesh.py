"""Device-mesh construction and parameter sharding rules.

The TPU-native replacement for the reference's process-ring topology: instead
of N OS processes connected by gRPC (src/dnet/shard/adapters/ring.py), chips
in one slice form a `jax.sharding.Mesh` with axes

  dp — data parallel (replicated params, sharded batch)
  pp — pipeline stages around the ring (layer axis of stacked params)
  tp — tensor parallel within a stage (Megatron column/row split)
  sp — sequence/context parallel (ring attention; KV sequence axis)

and the activation hop is `lax.ppermute` over `pp` inside one XLA program —
zero serialization, ICI bandwidth (SURVEY.md §2.9 north star).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP, AXIS_PP, AXIS_TP, AXIS_SP = "dp", "pp", "tp", "sp"

_distributed_up = False


def ensure_distributed(
    coordinator: str = "", num_processes: int = 0, process_id: int = 0
) -> bool:
    """Join a multi-host JAX runtime (idempotent).

    After joining, `jax.devices()` spans ALL hosts of the pod (ICI within
    a slice, DCN across), so mesh programs shard over the global device
    set.  This is a multi-CONTROLLER runtime: every process must dispatch
    the same programs in lockstep (SPMD batch/offline execution — e.g.
    each host running the same generate() script).  Request-driven HTTP
    serving across hosts goes through the gRPC shard ring instead
    (one dnet-shard per host; each shard may use its host-local mesh);
    api/server.py fails fast on that combination.

    Returns True when distributed mode is active.  num_processes == 0
    (the default) is single-process: no-op.
    """
    global _distributed_up
    if num_processes <= 0:
        return False
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"DNET_MESH_PROCESS_ID={process_id} out of range for "
            f"DNET_MESH_NUM_PROCESSES={num_processes}"
        )
    import jax  # local: keep module import light

    try:  # detect a runtime user code initialized directly
        already = jax._src.distributed.global_state.client is not None
    except AttributeError:  # private layout changed: trust our own flag
        already = False
    if not (_distributed_up or already) and not coordinator:
        # jax's cluster auto-detection only works under Slurm/TPU/MPI
        # metadata; anywhere else it raises an opaque internal error
        raise ValueError(
            "DNET_MESH_COORDINATOR (host:port of process 0) is required "
            f"when DNET_MESH_NUM_PROCESSES={num_processes} >= 1"
        )
    if _distributed_up or already:
        # already joined (by us or by user code calling jax.distributed
        # directly); a different topology cannot be honored — say so
        if not _distributed_up:
            _distributed_up = True
        if jax.process_count() != num_processes or jax.process_index() != process_id:
            raise RuntimeError(
                f"distributed runtime already initialized as process "
                f"{jax.process_index()}/{jax.process_count()}; cannot "
                f"re-join as {process_id}/{num_processes}"
            )
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _distributed_up = True
    return True


def parse_mesh(spec: str) -> Optional[Dict[str, int]]:
    """'pp=4,tp=2' -> {"pp": 4, "tp": 2}.  pp=0 means infer from devices.
    Shared by the server's --mesh flag and the offline generate CLI."""
    if not spec:
        return None
    out: Dict[str, int] = {}
    for part in spec.split(","):
        key, eq, val = part.partition("=")
        key = key.strip()
        if not eq or not val.strip():
            raise ValueError(f"--mesh expects axis=value pairs; got {part!r}")
        if key not in {"pp", "tp", "dp", "sp"}:
            raise ValueError(f"unknown mesh axis {key!r} in --mesh (use pp/tp/dp/sp)")
        try:
            n = int(val)
        except ValueError:
            raise ValueError(f"--mesh {key}={val!r} is not an integer") from None
        if n < 0 or (n == 0 and key != "pp"):
            raise ValueError(f"--mesh {key}={n} must be positive (pp=0 = infer)")
        out[key] = n
    return out


def build_mesh(
    pp: int = 1,
    tp: int = 1,
    dp: int = 1,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * pp * tp * sp
    if need > len(devices):
        raise ValueError(f"mesh {dp}x{pp}x{tp}x{sp} needs {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(dp, pp, tp, sp)
    return Mesh(grid, (AXIS_DP, AXIS_PP, AXIS_TP, AXIS_SP))


# ---- sharding rules for stacked layer params ------------------------------
# Stacked params have a leading layer axis; pp shards it.  Within a layer,
# column-parallel weights shard their output dim over tp, row-parallel their
# input dim.  Norm vectors replicate.

_COL_PARALLEL = {
    "wq", "wk", "wv", "w_gate", "w_up",  # [L, D, out] -> out/tp
    "wq_b", "wkv_b",  # deepseek MLA: head-dim outputs shard over tp
    "s_gate", "s_up",  # deepseek shared experts (dense col split)
}
_ROW_PARALLEL = {"wo", "w_down", "s_down"}  # [L, in, D] -> in/tp
_HEAD_VECTORS = {"bq", "bk", "bv", "sinks"}  # [L, out] -> out/tp
_EXPERT_SHARDED = {
    "gate_up", "down",  # gpt_oss [L, E, ..] -> E/tp (expert parallel)
    "e_gate", "e_up", "e_down",  # deepseek routed experts
}
_EXPERT_VECTORS = {"gate_up_b", "down_b"}  # [L, E, ..] -> E/tp


def layer_param_spec(name: str) -> P:
    if name in _COL_PARALLEL:
        return P(AXIS_PP, None, AXIS_TP)
    if name in _ROW_PARALLEL:
        return P(AXIS_PP, AXIS_TP, None)
    if name in _HEAD_VECTORS:
        return P(AXIS_PP, AXIS_TP)
    if name in _EXPERT_SHARDED:
        return P(AXIS_PP, AXIS_TP, None, None)
    if name in _EXPERT_VECTORS:
        return P(AXIS_PP, AXIS_TP, None)
    return P(AXIS_PP)  # norms, router, kind scalars: shard layer axis only


def window_param_specs(window_params: Dict) -> Dict:
    """Spec pytree for a stacked window; handles the two-level segment
    layout ({"dense": {...}, "moe": {...}}, deepseek) as well as flat."""
    out: Dict = {}
    for k, v in window_params.items():
        # "dense"/"moe": deepseek segments; "a"/"b": gpt_oss layer pairs
        if k in ("dense", "moe", "a", "b") and isinstance(v, dict):
            out[k] = {kk: layer_param_spec(kk) for kk in v}
        else:
            out[k] = layer_param_spec(k)
    return out


def shard_window_params(window_params: Dict, mesh: Mesh) -> Dict:
    """Place stacked layer params onto the mesh per the TP/PP rules."""

    def place(subtree, spec):
        return jax.device_put(subtree, NamedSharding(mesh, spec))

    specs = window_param_specs(window_params)
    out: Dict = {}
    for k, v in window_params.items():
        if isinstance(specs[k], dict):
            out[k] = {kk: place(v[kk], specs[k][kk]) for kk in v}
        else:
            out[k] = place(v, specs[k])
    return out


def replicate(tree, mesh: Mesh):
    return jax.device_put(tree, NamedSharding(mesh, P()))


def kv_spec(sp: bool = False) -> P:
    """KV cache [L, B, S, KVH, Hd]: layers over pp, kv-heads over tp, batch
    over dp; sequence over sp only when sequence parallelism is active (a
    size-1 sp annotation would still mark kv device-varying over sp inside
    shard_map and break the scan carry typing)."""
    return P(AXIS_PP, AXIS_DP, AXIS_SP if sp else None, AXIS_TP, None)
