"""Solver calibration: close the loop between predicted and measured stage
times.

The reference feeds device profiles into its MILP and never checks the
resulting cost model against reality — a stale or wrong profile silently
produces a bad ring (SURVEY.md §2.7; the profiler and solver never talk
again after the solve).  Here the loop closes:

  solve_topology records predicted per-stage seconds (solver.py);
  each shard can PROBE its real stage time (ShardCompute.probe_stage_time:
  the actual process() hot path on a synthetic decode frame);
  compare() turns the two into per-stage ratios;
  recalibrate() scales each device's measured-speed axes by its ratio so
  the next solve predicts what the hardware actually did.

Ratios are clamped: a probe hiccup (compile, GC pause) must nudge the
model, not poison it.
"""

from __future__ import annotations

from dataclasses import dataclass
from dataclasses import replace as dc_replace
from typing import Dict, List, Optional

from dnet_tpu.core.types import DeviceInfo, TopologyInfo
from dnet_tpu.utils.logger import get_logger

log = get_logger()

RATIO_CLAMP = (0.25, 4.0)


@dataclass
class StageCalibration:
    instance: str
    predicted_s: float
    measured_s: float

    @property
    def ratio(self) -> float:
        """measured / predicted (1.0 = cost model exact; >1 = device slower
        than the profile claims)."""
        if self.predicted_s <= 0:
            return 1.0
        return self.measured_s / self.predicted_s

    @property
    def rel_err(self) -> float:
        return abs(self.ratio - 1.0)

    def as_dict(self) -> dict:
        return {
            "instance": self.instance,
            "predicted_s": self.predicted_s,
            "measured_s": self.measured_s,
            "ratio": round(self.ratio, 4),
            "rel_err": round(self.rel_err, 4),
        }


def compare(
    topology: TopologyInfo, measured: Dict[str, float]
) -> List[StageCalibration]:
    """Join the solve-time predictions with measured per-stage seconds.

    measured: instance -> seconds/token (from the shard stage probes).
    Stages without a measurement are skipped (a dead shard mid-calibration
    must not fabricate a ratio).
    """
    predicted = topology.solution.get("predicted_stage_s") or []
    out: List[StageCalibration] = []
    for i, a in enumerate(topology.assignments):
        if a.instance not in measured:
            continue
        pred = predicted[i] if i < len(predicted) else 0.0
        out.append(
            StageCalibration(
                instance=a.instance,
                predicted_s=pred,
                measured_s=measured[a.instance],
            )
        )
    return out


def recalibrate(
    devices: List[DeviceInfo],
    calibrations: List[StageCalibration],
    clamp: tuple = RATIO_CLAMP,
) -> List[DeviceInfo]:
    """Scale each measured-speed axis by the observed ratio so the next
    solve's cost model predicts what the hardware actually did.

    A stage that ran r times slower than predicted means the device is r
    times slower than profiled: divide flops/bandwidths by the (clamped)
    ratio.  Devices without a calibration pass through unchanged.
    """
    by_instance = {c.instance: c for c in calibrations}
    out: List[DeviceInfo] = []
    for d in devices:
        c = by_instance.get(d.instance)
        if c is None or c.predicted_s <= 0 or c.measured_s <= 0:
            out.append(d)
            continue
        r = min(max(c.ratio, clamp[0]), clamp[1])
        out.append(
            dc_replace(
                d,
                flops_bf16=d.flops_bf16 / r,
                hbm_bw=d.hbm_bw / r,
                host_to_hbm_bw=d.host_to_hbm_bw / r,
            )
        )
    return out


def log_table(calibrations: List[StageCalibration]) -> None:
    for c in calibrations:
        log.info(
            "[PROFILE] calibrate %-20s predicted %.2fms measured %.2fms ratio %.2f",
            c.instance, c.predicted_s * 1e3, c.measured_s * 1e3, c.ratio,
        )


def max_rel_err(calibrations: List[StageCalibration]) -> Optional[float]:
    if not calibrations:
        return None
    return max(c.rel_err for c in calibrations)
