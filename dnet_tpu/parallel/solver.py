"""Topology solver: assign transformer layers to devices (HALDA analog).

The reference delegates to distilp's MILP ("HALDA", prima.cpp) producing
(w, n, k): layers per device, GPU-resident layers per device, rounds
(SURVEY.md §2.7).  TPU re-derivation with the same outputs:

- cost model per device i and layer count w_i, resident n_i:
    t_i(w) = w * t_compute_i                      (HBM-bound decode compute)
           + max(0, w - n) * layer_bytes / h2d_i  (host->HBM streaming, overlapped
                                                   but bounded by transfer rate)
           + t_comm_i                             (activation hop to next device)
  and the ring's per-token latency is sum_i t_i (sequential pipeline for one
  token) — minimizing the sum subject to full coverage.
- "greedy": proportional-to-speed assignment with memory-aware residency
  (exact for homogeneous slices: equal split, k=1).
- "milp": scipy HiGHS mixed-integer program minimizing total ring latency
  with integer w_i, n_i (heterogeneous clusters, the reference's regime).

k > 1 (multi-round rings) follows the reference (api/utils.py:62-131): when
HBM residency cannot hold a device's assignment (n_i < w_i), layers are
dealt in k contiguous rounds — the device appears k times per token pass
and each visit's weights prefetch while the REST of the ring computes,
which is the reference's "no memory ceiling" regime (405B over small
hosts).  `choose_rounds` picks k; `deal_rounds` deals the chunks; shards
execute rounds natively (shard/compute.py:_process_round).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional

from dnet_tpu.core.types import DeviceInfo, LayerAssignment, TopologyInfo
from dnet_tpu.utils.logger import get_logger

log = get_logger()


@dataclass
class ModelProfile:
    """Per-model cost inputs (≙ distilp.profile_model)."""

    model_id: str
    num_layers: int
    layer_bytes: int  # parameter bytes per layer (serving dtype)
    layer_flops_per_token: float  # forward FLOPs per token per layer
    kv_bytes_per_token_per_layer: int
    edge_bytes: int = 0  # embed + head + final norm
    seq_len: int = 4096
    # models with paired/segmented window layouts (gpt_oss, deepseek_v2)
    # cannot execute k-round fit stacks yet: fail at SOLVE time, not on the
    # first request (core/engine.py:apply_round raises otherwise)
    multi_round_ok: bool = True
    # KV-head count: mesh-backed shards shard KV heads over tp
    # (parallel/mesh.py kv_spec), so a node's mesh_tp must divide this.
    # 0 = unknown: leave mesh_tp unclamped.
    tp_heads: int = 0
    # bytes of one hidden-state row (hidden_size x serving elem size):
    # what each of the two per-layer TP collectives moves per token.
    # 0 = unknown: TP collective cost is not charged.
    hidden_bytes: int = 0


@dataclass
class SolveResult:
    w: List[int]
    n: List[int]
    k: int = 1
    obj_value: float = 0.0
    solver: str = "greedy"


def device_throughput(d: DeviceInfo, m: ModelProfile) -> float:
    """Per-layer decode time (s): max of FLOP time and HBM-read time.

    chip_count > 1 = a mesh-backed shard (parallel/shard_mesh.py): the ring
    node is a whole host-local slice, so its FLOPs and aggregate HBM
    bandwidth scale with the chips running the window tensor-parallel —
    the solver sees ONE node with the slice's combined speed."""
    c = max(d.chip_count, 1)
    flops_t = m.layer_flops_per_token / max(d.flops_bf16 * c, 1e9)
    hbm_t = m.layer_bytes / max(d.hbm_bw * c, 1e9)
    return max(flops_t, hbm_t)


def hbm_layer_capacity(d: DeviceInfo, m: ModelProfile, reserve_frac: float = 0.15) -> int:
    """How many layers fit in HBM after KV + edge + headroom.  A mesh-backed
    shard (chip_count > 1) pools the slice's HBM: params and KV shard over
    tp, only the edge weights replicate per chip."""
    if d.hbm_bytes <= 0:
        return m.num_layers  # unknown: assume everything fits
    c = max(d.chip_count, 1)
    kv = m.kv_bytes_per_token_per_layer * m.seq_len
    usable = d.hbm_bytes * c * (1 - reserve_frac) - m.edge_bytes * c
    per_layer = m.layer_bytes + kv
    return max(int(usable // per_layer), 0)


def host_layer_capacity(d: DeviceInfo, m: ModelProfile) -> int:
    """Layers whose params fit in host DRAM (offload ceiling)."""
    if d.host_ram_bytes <= 0:
        return m.num_layers
    return max(int((d.host_ram_bytes * 0.8) // m.layer_bytes), 0)


def solve_greedy(devices: List[DeviceInfo], m: ModelProfile) -> SolveResult:
    """Proportional-to-speed with memory-aware residency."""
    L = m.num_layers
    speeds = [1.0 / device_throughput(d, m) for d in devices]
    total = sum(speeds)
    raw = [L * s / total for s in speeds]
    w = [int(math.floor(r)) for r in raw]
    # deal remaining layers by largest fractional part
    rem = L - sum(w)
    order = sorted(range(len(devices)), key=lambda i: raw[i] - w[i], reverse=True)
    for i in order[:rem]:
        w[i] += 1
    # cap by host capacity (a device cannot even stream more than this)
    for i, d in enumerate(devices):
        cap = host_layer_capacity(d, m)
        if w[i] > cap:
            w[i] = cap
    deficit = L - sum(w)
    if deficit > 0:
        # push the overflow to devices with spare host capacity, fastest first
        for i in sorted(range(len(devices)), key=lambda i: speeds[i], reverse=True):
            spare = host_layer_capacity(devices[i], m) - w[i]
            take = min(spare, deficit)
            w[i] += take
            deficit -= take
            if deficit == 0:
                break
        if deficit > 0:
            raise ValueError(
                f"model does not fit: {deficit} layers have no host to live on"
            )
    n = [min(w[i], hbm_layer_capacity(d, m)) for i, d in enumerate(devices)]
    obj = _ring_latency(devices, m, w, n)
    return SolveResult(w=w, n=n, k=1, obj_value=obj, solver="greedy")


def predict_stage_time(d: DeviceInfo, m: ModelProfile, w_i: int, n_i: int) -> float:
    """Predicted per-token seconds for one device's stage: window compute
    (TP speedup is already in device_throughput — FLOPs and HBM bandwidth
    scale with chip_count) MINUS nothing, PLUS what TP costs: two ring
    all-reduces per layer over the hidden row, 2(c-1)/c x hidden_bytes
    per link each (parallel/tp_collectives.py collective_bytes), plus
    host->HBM streaming of non-resident layers.  Excludes the activation
    hop (t_comm) so it is directly comparable to an on-device stage probe
    (parallel/calibrate.py).  Devices with unknown ici_bw (0) charge no
    collective cost — identical predictions to the pre-TP solver."""
    t = w_i * device_throughput(d, m)
    c = max(d.chip_count, 1)
    if c > 1 and d.ici_bw > 0 and m.hidden_bytes > 0:
        per_collective = 2.0 * (c - 1) / c * m.hidden_bytes / d.ici_bw
        t += w_i * 2 * per_collective
    t += max(0, w_i - n_i) * m.layer_bytes / max(d.host_to_hbm_bw, 1e9)
    return t


def _ring_latency(devices, m, w, n) -> float:
    return sum(
        predict_stage_time(d, m, w[i], n[i]) + d.t_comm for i, d in enumerate(devices)
    )


def solve_milp(devices: List[DeviceInfo], m: ModelProfile, mip_gap: float = 1e-4) -> SolveResult:
    """Exact (w, n) via scipy HiGHS MILP.

    Variables per device: w_i (int), n_i (int), s_i >= w_i - n_i (streamed
    layers), plus T = bottleneck stage time.  Objective: minimize T (pipeline
    throughput under multiple in-flight tokens is set by the slowest stage)
    with a small sum-latency tiebreak so homogeneous cases balance exactly.
    Constraints: per-stage time <= T, sum w = L, n_i <= hbm-cap_i,
    n_i <= w_i, w_i <= host-cap_i.
    """
    import numpy as np
    from scipy.optimize import Bounds, LinearConstraint, milp

    D = len(devices)
    L = m.num_layers
    c = np.array([device_throughput(d, m) for d in devices])
    h = np.array(
        [m.layer_bytes / max(d.host_to_hbm_bw, 1e9) for d in devices]
    )
    hbm_cap = np.array([hbm_layer_capacity(d, m) for d in devices])
    host_cap = np.array([host_layer_capacity(d, m) for d in devices])

    # x = [w_0..w_D-1, n_0..n_D-1, s_0..s_D-1, T]
    N = 3 * D + 1
    eps = 1e-3 / max(L, 1)
    cost = np.concatenate([eps * c, np.zeros(D), eps * h, [1.0]])
    integrality = np.concatenate([np.ones(D), np.ones(D), np.zeros(D), [0.0]])
    lb = np.zeros(N)
    ub = np.concatenate([host_cap, hbm_cap, np.full(D, L), [np.inf]])
    constraints = []
    # sum w == L
    a = np.zeros(N)
    a[:D] = 1
    constraints.append(LinearConstraint(a, L, L))
    for i in range(D):
        # n_i - w_i <= 0
        a = np.zeros(N)
        a[D + i] = 1
        a[i] = -1
        constraints.append(LinearConstraint(a, -np.inf, 0))
        # w_i - n_i - s_i <= 0
        a = np.zeros(N)
        a[i] = 1
        a[D + i] = -1
        a[2 * D + i] = -1
        constraints.append(LinearConstraint(a, -np.inf, 0))
        # stage time: w_i*c_i + s_i*h_i - T <= -t_comm_i (comm folded in)
        a = np.zeros(N)
        a[i] = c[i]
        a[2 * D + i] = h[i]
        a[3 * D] = -1
        constraints.append(LinearConstraint(a, -np.inf, -devices[i].t_comm))

    res = milp(
        c=cost,
        constraints=constraints,
        integrality=integrality,
        bounds=Bounds(lb, ub),
        options={"mip_rel_gap": mip_gap},
    )
    if not res.success:
        log.warning("MILP infeasible/failed (%s); falling back to greedy", res.message)
        return solve_greedy(devices, m)
    w = [int(round(v)) for v in res.x[:D]]
    n = [int(round(v)) for v in res.x[D : 2 * D]]
    # MILP maximizes residency implicitly only via streaming cost; pin n to
    # the max that fits (streaming fewer layers never hurts)
    n = [min(w[i], int(hbm_cap[i])) for i in range(D)]
    obj = _ring_latency(devices, m, w, n)
    return SolveResult(w=w, n=n, k=1, obj_value=obj, solver="milp")


def order_devices(devices: List[DeviceInfo]) -> List[DeviceInfo]:
    """Ring ordering: group ICI-adjacent devices so in-slice hops dominate
    (the reference's Thunderbolt-adjacency greedy, api/utils.py:134-193)."""
    if not devices:
        return []
    remaining = list(devices)
    out = [remaining.pop(0)]
    while remaining:
        cur = out[-1]
        nxt_i = 0
        for i, cand in enumerate(remaining):
            if cand.ici_adjacent(cur):
                nxt_i = i
                break
        out.append(remaining.pop(nxt_i))
    return out


def postprocess_merge_singletons(
    devices: List[DeviceInfo], w: List[int], n: List[int], m: ModelProfile
) -> tuple[List[DeviceInfo], List[int], List[int]]:
    """Merge single-layer devices into their lighter neighbor (reference
    postprocess_single_round, api/utils.py:12-59) — a 1-layer stage rarely
    pays for its hop."""
    if len(devices) <= 1:
        return devices, w, n
    while True:
        try:
            i = next(idx for idx, wi in enumerate(w) if wi == 1 and len(w) > 1)
        except StopIteration:
            return devices, w, n
        left = (i - 1) % len(w)
        right = (i + 1) % len(w)
        j = left if w[left] <= w[right] else right
        if j == i:
            return devices, w, n
        w[j] += w[i]
        n[j] = min(w[j], hbm_layer_capacity(devices[j], m))
        del devices[i], w[i], n[i]


def choose_rounds(w: List[int], n: List[int], max_rounds: int = 4) -> int:
    """k for the multi-round ring (reference HALDA's k): when HBM residency
    cannot hold a device's whole assignment (n_i < w_i), dealing the layers
    in k contiguous chunks lets each visit's weights prefetch while the REST
    of the ring computes — the reference's extreme-memory-pressure regime
    (api/utils.py:62-131).  k = 1 when everything is resident."""
    k = 1
    for wi, ni in zip(w, n):
        if 0 < ni < wi:
            k = max(k, math.ceil(wi / ni))
        elif ni == 0 and wi > 0:
            k = max_rounds  # fully streamed device: cap
    return min(k, max_rounds)


def deal_rounds(w: List[int], k: int) -> List[List[List[int]]]:
    """Deal each device's w_i layers into k contiguous chunks, iterating
    rounds-outer/devices-inner so global layer order follows the ring k
    times (reference compute_layer_assignments, api/utils.py:62-131).
    Returns per-device round lists."""
    rounds: List[List[List[int]]] = [[] for _ in w]
    start = 0
    for r in range(k):
        for i, wi in enumerate(w):
            size = wi // k + (1 if r < wi % k else 0)
            if size:
                rounds[i].append(list(range(start, start + size)))
                start += size
    return rounds


def merge_mesh_slices(
    devices: List[DeviceInfo],
) -> tuple[List[DeviceInfo], dict]:
    """Mesh-slice candidates: ICI-adjacent devices (same host, same slice)
    with a KNOWN interconnect bandwidth collapse into ONE multi-chip
    DeviceInfo — a v5litepod-4 host registered as four 1-chip shards
    becomes one 4-chip mesh slice whose window runs tensor-parallel
    (parallel/tp.py).  Returns (merged device list, {surviving instance:
    [absorbed instances]}); callers adopt the merge only when the solved
    ring latency actually improves (fewer hops + TP speedup vs the new
    collective cost — predict_stage_time models both sides).  Devices
    with ici_bw == 0 never merge: the collective cost would be a guess.
    """
    groups: dict = {}
    order: list = []
    for d in devices:
        key = (d.host, d.slice_id)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(d)
    merged: List[DeviceInfo] = []
    members: dict = {}
    from dataclasses import replace as _dc_replace

    for key in order:
        g = groups[key]
        if len(g) < 2 or any(d.ici_bw <= 0 for d in g):
            merged.extend(g)
            continue
        head = _dc_replace(
            g[0],
            chip_count=sum(max(d.chip_count, 1) for d in g),
            # members share one ICI fabric; cost with the slowest link
            ici_bw=min(d.ici_bw for d in g),
        )
        members[head.instance] = [d.instance for d in g[1:]]
        merged.append(head)
    return merged, members


def solve_topology(
    devices: List[DeviceInfo],
    m: ModelProfile,
    kv_bits: int = 0,
    solver: str = "auto",
    mip_gap: float = 1e-4,
    max_rounds: int = 4,
) -> TopologyInfo:
    """Full solve: slice-merge -> order -> (w, n) -> merge -> k rounds ->
    assignments."""
    if not devices:
        raise ValueError("no devices")
    from dataclasses import replace as _dc_replace

    def _clamp_and_solve(devs_in: List[DeviceInfo]):
        # clamp each node's usable chip count BEFORE costing: mesh-backed
        # shards shard KV heads over tp (kv_spec), so a 4-chip host
        # serving a 2-kv-head model runs tp=2 — sizing its layer share
        # with 4-chip pooled HBM would overcommit the 2 chips that
        # actually serve
        clamped = []
        chips = {}  # instance -> physical chip count (pre-clamp)
        for d in devs_in:
            chips[d.instance] = max(d.chip_count, 1)
            c = max(d.chip_count, 1)
            while c > 1 and m.tp_heads > 0 and m.tp_heads % c != 0:
                c -= 1
            clamped.append(
                _dc_replace(d, chip_count=c) if c != d.chip_count else d
            )
        ordered = order_devices(clamped)
        heterogeneous = len(
            {(d.chip_kind, d.chip_count, round(d.flops_bf16 / 1e12, 1))
             for d in ordered}
        ) > 1
        use_milp = solver == "milp" or (solver == "auto" and heterogeneous)
        res = (
            solve_milp(ordered, m, mip_gap) if use_milp
            else solve_greedy(ordered, m)
        )
        return ordered, chips, res

    # mesh-slice placement (ROADMAP item 3): when ICI-adjacent devices can
    # pool into one multi-chip hop, solve BOTH layouts and keep the one
    # with the lower predicted ring latency — one 4-chip tp hop beats four
    # 1-chip hops exactly when the interconnect outruns the ring wire
    # (t_comm), which is what the objective compares.
    slice_members: dict = {}
    slice_candidates, candidate_members = merge_mesh_slices(devices)
    devices_ordered, orig_chips, result = _clamp_and_solve(devices)
    if candidate_members:
        base_obj = result.obj_value
        m_devs, m_chips, m_res = _clamp_and_solve(slice_candidates)
        if m_res.obj_value < base_obj:
            devices_ordered, orig_chips, result = m_devs, m_chips, m_res
            slice_members = candidate_members
            log.info(
                "mesh-slice placement: merged %s (ring latency %.4fs -> "
                "%.4fs)", candidate_members, base_obj, m_res.obj_value,
            )
    devices = devices_ordered
    devs = list(devices)
    w, n = list(result.w), list(result.n)
    devs, w, n = postprocess_merge_singletons(devs, w, n, m)

    # drop zero-layer devices
    keep = [i for i in range(len(devs)) if w[i] > 0]
    devs = [devs[i] for i in keep]
    w = [w[i] for i in keep]
    n = [n[i] for i in keep]

    k = 1
    if len(devs) > 1 and m.multi_round_ok:
        k = choose_rounds(w, n, max_rounds)
    per_dev_rounds = deal_rounds(w, k)

    assignments: List[LayerAssignment] = []
    for i, d in enumerate(devs):
        layers = [a for r in per_dev_rounds[i] for a in r]
        window = 0 if n[i] >= w[i] else max(n[i] // 2, 1)
        # multi-chip hosts serve their window tensor-parallel over the local
        # slice (parallel/shard_mesh.py); a streaming window composes — each
        # layer streams in tp/sp-sharded (see the r5 note below).
        # chip_count is already clamped to a KV-head-divisible tp above;
        # chips the clamp left over become a SEQUENCE-parallel axis (KV
        # shards over them) instead of idling — e.g. a 4-chip host serving
        # a 2-kv-head model runs tp=2 x sp=2.  The cost model stays on the
        # clamped count (conservative: sp's KV-capacity win is unmodeled).
        mesh_tp = max(d.chip_count, 1)
        mesh_sp = 1
        spare = orig_chips.get(d.instance, mesh_tp) // mesh_tp
        # largest sp <= spare dividing the sequence (all-or-nothing would
        # idle chips whenever the full spare count doesn't divide)
        for s in range(spare, 1, -1):
            if m.seq_len % s == 0:
                mesh_sp = s
                break
        residency = 0 if n[i] >= w[i] else n[i]
        # streaming COMPOSES with the mesh shard (r5): each window layer
        # streams host->mesh as tp/sp-sharded device_puts, so the window
        # lives in the slice's POOLED HBM — exactly the capacity n[i] was
        # sized against.  No single-chip fallback, no re-derivation.
        # NamedSharding TP (parallel/tp.py): a pure-TP shard — multi-chip,
        # no sp axis, fully resident window — gets an explicit tp_degree
        # that rides the load body into ShardCompute and selects the TP
        # substrate with the quantizable collectives.  sp/streaming combos
        # pin tp_degree=1 and stay on the shard_map mesh substrate.
        tp_degree = mesh_tp if (mesh_sp == 1 and window == 0) else 1
        assignments.append(
            LayerAssignment(
                instance=d.instance,
                layers=layers,
                rounds=per_dev_rounds[i],
                window_size=window,
                residency_size=residency,
                # both axes EXPLICIT (1 = pinned single, never 0 = "shard
                # default"): a shard-side DNET_SHARD_MESH_* env must not
                # override a solve that decided against the mesh
                mesh_tp=mesh_tp,
                mesh_sp=mesh_sp,
                tp_degree=tp_degree,
            )
        )
    for i, a in enumerate(assignments):
        a.next_instance = assignments[(i + 1) % len(assignments)].instance
    solution = {
        "k": k,
        "w": w,
        "n": n,
        "obj_value": result.obj_value,
        "solver": result.solver,
        # per-stage predictions recorded at solve time so the
        # calibration loop (parallel/calibrate.py) can compare them
        # against measured probes without re-deriving the model profile
        "predicted_stage_s": [
            predict_stage_time(d, m, w[i], n[i]) for i, d in enumerate(devs)
        ],
        "tp_degree": [a.tp_degree for a in assignments],
    }
    if slice_members:
        # surviving instance -> the ICI-adjacent instances it absorbed
        # (those shards receive no layers; their chips serve inside the
        # surviving shard's mesh slice)
        solution["mesh_slices"] = slice_members
    return TopologyInfo(
        model=m.model_id,
        num_layers=m.num_layers,
        kv_bits=kv_bits,
        devices=devs,
        assignments=assignments,
        solution=solution,
    )


def model_profile_from_checkpoint(
    model_dir,
    seq_len: int = 4096,
    kv_bits: int = 0,
    weight_quant_bits: int = 0,
    quant_group: int = 0,  # 0 = the quantizer's default group size
) -> ModelProfile:
    """Cost model from checkpoint headers (no weight loading)."""
    import json
    from pathlib import Path

    from dnet_tpu.models.base import ModelConfig
    from dnet_tpu.utils.checkpoint import Checkpoint

    ckpt = Checkpoint(model_dir)
    cfg = ModelConfig.from_hf(ckpt.config)
    layer_bytes = ckpt.layer_nbytes(0)
    if weight_quant_bits in (4, 8):
        # weight-only serving (ops/quant.py): bits/8 bytes per elem +
        # per-group scales, vs the checkpoint's 2-byte elems.  Norm/bias
        # tensors stay float but are a rounding error at layer scale.
        from dnet_tpu.ops.quant import DEFAULT_GROUP, DEFAULT_GROUP_Q4

        group = quant_group or (
            DEFAULT_GROUP_Q4 if weight_quant_bits == 4 else DEFAULT_GROUP
        )
        layer_bytes = int(layer_bytes * (weight_quant_bits / 8 + 2 / group) / 2)
    edge_bytes = sum(
        _tensor_bytes(ckpt, name) for name in ckpt.edge_tensors
    )
    D = cfg.hidden_size
    # decode FLOPs/token/layer ~ 2 * params_per_layer (dense); MoE uses top-k
    params_per_layer = layer_bytes / 2  # serving bf16
    active = params_per_layer
    if cfg.num_local_experts and cfg.num_experts_per_tok:
        active = params_per_layer * (
            cfg.num_experts_per_tok / cfg.num_local_experts
        )
    kvh = cfg.num_key_value_heads
    if kv_bits == 8:  # int8 + per-(pos,head) f32 scale (core/kvcache.py)
        kv_bytes = 2 * kvh * (cfg.head_dim + 4)
    elif kv_bits == 4:  # packed nibbles + f32 scale
        kv_bytes = 2 * kvh * (cfg.head_dim // 2 + 4)
    else:
        kv_bytes = 2 * kvh * cfg.head_dim * 2
    return ModelProfile(
        model_id=str(model_dir),
        tp_heads=cfg.num_key_value_heads or cfg.num_attention_heads or 0,
        hidden_bytes=D * 2,  # serving bf16 activations
        multi_round_ok=cfg.model_type not in ("gpt_oss", "deepseek_v2"),
        num_layers=cfg.num_hidden_layers,
        layer_bytes=layer_bytes,
        layer_flops_per_token=2.0 * active,
        kv_bytes_per_token_per_layer=kv_bytes,
        edge_bytes=edge_bytes,
        seq_len=seq_len,
    )


def _tensor_bytes(ckpt, name: str) -> int:
    shape, dtype = ckpt.tensor_meta(name)
    from dnet_tpu.utils.checkpoint import _dtype_size

    n = 1
    for s in shape:
        n *= s
    return n * _dtype_size(dtype)
