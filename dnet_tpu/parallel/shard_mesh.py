"""MeshShardEngine: one gRPC ring shard backed by a LOCAL device mesh.

Composes the two serving substrates (VERDICT r3 next #1): the process ring
(gRPC frames between hosts, shard/adapter.py) and the in-slice mesh
(shard_map + psum over ICI, parallel/ring.py).  Where the reference gives
every ring node exactly one accelerator (src/dnet/shard/adapters/ring.py:
410-450 — one process, one Metal device), a TPU host owns a 4-8 chip ICI
slice; this engine lets ONE ring shard drive that whole slice: its layer
window runs tensor-parallel (and optionally sequence-parallel) across the
local chips, while activations still hop host-to-host over gRPC/DCN.

The north-star v5e-16 topology (BASELINE.md) becomes expressible:
4 hosts x 4 chips = a 4-shard gRPC ring where each shard is a tp=4 mesh.

Design: LocalEngine's shard step functions (_embed_window / _hidden /
_hidden_round / _hidden_tail, core/engine.py:279-407) are rebuilt as
shard_map programs over a pp=1 x tp x sp mesh.  Params place with the same
column/row-parallel rules as the full mesh ring (parallel/mesh.py), the KV
cache shards heads over tp (sequence over sp), and the models' existing
tp_axis/sp_axis seams provide the psums — no new model code.  Everything
else (sessions, sampling invariants, the ShardCompute hot loop) is
inherited unchanged: one implementation, three execution substrates.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from dnet_tpu.core.engine import LocalEngine, Session
from dnet_tpu.core.sampler import pack_chunk_results, sample
from dnet_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    build_mesh,
    kv_spec,
    window_param_specs,
)
from dnet_tpu.utils.jax_compat import pcast_varying, shard_map
from dnet_tpu.utils.logger import get_logger

log = get_logger()


class MeshShardEngine(LocalEngine):
    """LocalEngine shard-mode compute core over a host-local tp x sp mesh.

    Drop-in for LocalEngine inside ShardCompute: same jitted-fn surface,
    same Session contract, the window math runs SPMD over `devices`.
    """

    def __init__(
        self,
        model_dir: str | Path,
        layers: Sequence[int],
        tp: int = 1,
        sp: int = 1,
        devices: Optional[Sequence] = None,
        max_seq: int = 2048,
        param_dtype: str = "bfloat16",
        kv_dtype: Optional[str] = None,
        kv_ttl_s: float = 600.0,
        kv_quant_bits: int = 0,
        weight_quant_bits: int = 0,
        weight_quant_group: int = 0,
        window_size: int = 0,
        residency_size: int = 0,
        repack_dir: Optional[str] = None,
        spec_lookahead: int = 0,
    ) -> None:
        if tp * sp < 1:
            raise ValueError(f"mesh axes tp={tp} sp={sp} must be positive")
        if sp > 1 and max_seq % sp != 0:
            raise ValueError(f"sp={sp} must divide max_seq={max_seq}")
        self.tp, self.sp = tp, sp
        self.mesh = build_mesh(pp=1, tp=tp, dp=1, sp=sp, devices=devices)
        super().__init__(
            model_dir,
            layers=list(layers),
            max_seq=max_seq,
            param_dtype=param_dtype,
            kv_dtype=kv_dtype,
            kv_ttl_s=kv_ttl_s,
            shard_mode=True,
            window_size=window_size,
            residency_size=residency_size,
            repack_dir=repack_dir,
            kv_quant_bits=kv_quant_bits,
            weight_quant_bits=weight_quant_bits,
            weight_quant_group=weight_quant_group,
            spec_lookahead=spec_lookahead,
        )

    # quant scale-group divisibility: same fail-fast as the full mesh ring
    from dnet_tpu.parallel.engine import MeshEngine as _ME

    _check_quant_sharding = _ME._check_quant_sharding
    del _ME

    # ---- substrate hooks ----------------------------------------------
    # The mesh-specific choices — axis names, param/KV specs, placement —
    # are isolated here so parallel/tp.py's TpEngine (NamedSharding over a
    # ("batch", "model") mesh with the quantizable collective seam) can
    # subclass this engine and override ONLY these; every program builder
    # below is substrate-agnostic.

    def _tp_axis(self):
        """Axis object handed to apply_window's tp seam (a plain string =
        exact psum; parallel/tp_collectives.TpAxis = quantizable).  Kept
        even at tp=1: the size-1 psum certifies x over the axis for the
        replicated out_spec."""
        return AXIS_TP

    def _sp_axis(self):
        return AXIS_SP if self.sp > 1 else None

    def _certify_axes(self):
        """Size-1 mesh axes the window output must be marked varying over
        (and psum-certified back) so the scan carry types line up."""
        return (AXIS_PP, AXIS_DP)

    def _window_specs_of(self, tree):
        return window_param_specs(tree)

    def _kv_pspec(self):
        return kv_spec(self._sp_axis() is not None)

    def _place_window(self, host_tree):
        """Window params host -> mesh, PRE-SHARDED: each chip's slice is
        cast and uploaded individually (parallel/tp.py place_presharded),
        so neither the host cast buffer nor any device ever materializes
        the full stacked tensor — load peak is 1/tp per chip."""
        from dnet_tpu.parallel.tp import place_presharded

        return place_presharded(
            host_tree, self.mesh, self._window_specs_of(host_tree),
            cast=self._np_cast,
        )

    def _place_edge(self, host_edge):
        from dnet_tpu.parallel.mesh import replicate

        return replicate(jax.tree.map(self._np_cast, host_edge), self.mesh)

    def _place_kv(self, kv):
        from jax.sharding import NamedSharding

        spec = self._kv_pspec()
        return jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(self.mesh, spec)), kv
        )

    # ---- loading ------------------------------------------------------
    def _np_cast(self, a):
        """Cast on HOST (numpy + ml_dtypes): the stacked window must not
        transit a single device's HBM before mesh placement — the whole
        point of a mesh shard is a window larger than one chip.  Called
        per SLICE by the pre-sharded placement path, so the cast copy is
        slice-sized too."""
        arr = np.asarray(a)
        if np.issubdtype(arr.dtype, np.floating):
            import ml_dtypes

            target = (
                ml_dtypes.bfloat16
                if self.param_dtype == jnp.bfloat16
                else self.param_dtype
            )
            arr = arr.astype(target)
        return arr

    def _load_params(self) -> None:
        t0 = time.perf_counter()
        m = self.model
        if self.weight_quant_bits and not m.supports_weight_quant:
            raise NotImplementedError(
                f"weight quantization not supported for {self.config.model_type}"
            )
        if self.plan.streams_weights:
            # streaming x mesh (VERDICT r4 next #2): each window layer
            # streams host->mesh as tp/sp-SHARDED device_puts — the window
            # lives across the slice's pooled HBM, not one chip's.  The
            # host store and residency machinery are LocalEngine's
            # (core/weights.py); only the placement differs.
            # Ref prefetch pipeline analog:
            # /root/reference/src/dnet/shard/policies/offload.py:395-421
            from dnet_tpu.core.weights import HostLayerStore, WeightCache

            store = HostLayerStore(
                self.ckpt,
                m,
                param_dtype=str(self.param_dtype),
                repack_dir=self._repack_dir,
                weight_quant_bits=self.weight_quant_bits,
                weight_quant_group=self.weight_quant_group,
            )
            probe = store.layer_host(m.layers[0])
            if self.weight_quant_bits:
                self._check_quant_sharding(probe)
            self._window_specs = self._window_specs_of(probe)
            self.weight_cache = WeightCache(
                store,
                max_resident=self.plan.residency,
                put_fn=self._place_window,
            )
            w = self.plan.window_size
            self._windows = [
                m.layers[i : i + w] for i in range(0, len(m.layers), w)
            ]
            self.window_params = None
            self.weight_cache.prefetch(self._windows[0])
            self._load_edge(t0)
            return
        per_layer = [m.map_layer(self.ckpt.load_layer_raw(a)) for a in m.layers]
        stacked = m.stack_layers(per_layer)
        if self.weight_quant_bits:
            stacked = m.quantize_params(
                stacked, self.weight_quant_bits, scale_dtype=self.param_dtype,
                group_size=self.weight_quant_group,
            )
            self._check_quant_sharding(stacked)
        # pre-sharded placement: cast + upload happen per chip-slice, so
        # the full stacked window is never materialized post-cast on host
        # nor on any single chip (satellite fix: load peak 1/tp per chip)
        self._window_specs = self._window_specs_of(stacked)
        self.window_params = self._place_window(stacked)
        self._load_edge(t0)

    def _load_edge(self, t0: float) -> None:
        """Edge load/prune/quantize/place, shared by the resident and
        streaming branches (pruning identical to LocalEngine._load_params)."""
        m = self.model
        edge_raw = m.map_edge(self.ckpt.load_edge_raw())
        tied = self.config.tie_word_embeddings
        if not (m.is_first or (m.is_last and tied)):
            edge_raw.pop("embed", None)
        if not m.is_last:
            edge_raw.pop("final_norm", None)
            edge_raw.pop("lm_head", None)
        if self.weight_quant_bits:
            edge_raw = m.quantize_edge(
                edge_raw, self.weight_quant_bits, scale_dtype=self.param_dtype,
                group_size=self.weight_quant_group,
            )
        self.edge_params = self._place_edge(edge_raw)
        log.info(
            "[PROFILE] mesh-shard %s %d layers over tp=%d sp=%d in %.2fs",
            "streams" if self.plan.streams_weights else "placed",
            len(m.layers), self.tp, self.sp, time.perf_counter() - t0,
        )

    # ---- jitted step functions ---------------------------------------
    def _build_fns(self) -> None:
        model, mesh = self.model, self.mesh
        tp_axis = self._tp_axis()
        sp_axis = self._sp_axis()
        certify = self._certify_axes()
        has_kinds = getattr(model, "layer_kinds", None) is not None
        kinds_arr = model.layer_kinds if has_kinds else jnp.zeros((), jnp.int32)
        kvs = self._kv_pspec()
        in_specs = (self._window_specs, P(), kvs, P(), P(), P())
        out_specs = (P(), kvs)

        def window_core(wp, x, kv, pos, t_real, kinds):
            # tp collective seams + sp flash-decoding combines live in the
            # models (same seams the in-slice ring uses, parallel/ring.py);
            # pp=1 here — the PIPELINE is the gRPC ring outside this program.
            # x becomes device-varying over the size-1 certify axes once the
            # sharded params/kv touch it; mark it up front so the layer
            # scan's carry types line up.
            x = pcast_varying(x, certify)
            x, kv = model.apply_window(
                wp, x, kv, pos,
                layer_kinds=kinds if has_kinds else None,
                tp_axis=tp_axis, sp_axis=sp_axis, t_real=t_real,
            )
            # the certify axes are size 1, so the psum is an identity that
            # just certifies x as replicated again for the P() out_spec
            x = jax.lax.psum(x, certify)
            return x, kv

        core = shard_map(
            window_core, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )

        def hidden_step(window_params, x, kv, pos, t_real, kinds=None):
            k = kinds if kinds is not None else kinds_arr
            return core(window_params, x, kv, pos, t_real, k)

        self._hidden = jax.jit(hidden_step, donate_argnums=(2,))

        if self.plan.streams_weights:
            # streaming feeds _hidden SINGLE-layer trees whose structure can
            # vary layer to layer (two-segment models wrap each layer as
            # {"dense": ...} OR {"moe": ...}, models/segments.py:87-89), but
            # shard_map bakes in_specs at build time — so dispatch on the
            # incoming tree structure and build one program per structure
            # (same retrace-on-structure behavior LocalEngine streaming gets
            # from plain jit)
            progs: dict = {}

            def hidden_stream(window_params, x, kv, pos, t_real, kinds=None):
                key = jax.tree.structure(window_params)
                fn = progs.get(key)
                if fn is None:
                    seg_core = shard_map(
                        window_core, mesh=mesh,
                        in_specs=(
                            self._window_specs_of(window_params),
                            P(), kvs, P(), P(), P(),
                        ),
                        out_specs=out_specs,
                    )

                    def step(wp, x, kv, pos, t_real, kinds=None, _c=seg_core):
                        k = kinds if kinds is not None else kinds_arr
                        return _c(wp, x, kv, pos, t_real, k)

                    fn = jax.jit(step, donate_argnums=(2,))
                    progs[key] = fn
                return fn(window_params, x, kv, pos, t_real, kinds)

            self._hidden = hidden_stream

        def hidden_round(window_params, x, kv, pos, t_real, lo, hi, kinds=None):
            """One ring ROUND (k-round schedule): static [lo, hi) slice of
            the stacked window — slicing runs OUTSIDE shard_map where the
            layer axis is pp=1-replicated, so XLA slices each device's
            local shard in place."""
            wp = jax.tree.map(lambda a: a[lo:hi], window_params)
            kv_r = jax.tree.map(lambda a: a[lo:hi], kv)
            k = kinds_arr[lo:hi] if has_kinds else kinds_arr
            x, kv_r = core(wp, x, kv_r, pos, t_real, k)
            kv = jax.tree.map(lambda f, s: f.at[lo:hi].set(s), kv, kv_r)
            return x, kv

        self._hidden_round = jax.jit(
            hidden_round, static_argnums=(5, 6), donate_argnums=(2,)
        )

        def embed_window(window_params, edge_params, tokens, kv, pos, t_real):
            x = model.embed(edge_params, tokens)
            return core(window_params, x, kv, pos, t_real, kinds_arr)

        self._embed_window = jax.jit(embed_window, donate_argnums=(3,))

        def hidden_tail(window_params, edge_params, x, kv, pos, last_idx, sp, key, counts):
            x, kv = core(window_params, x, kv, pos, last_idx + 1, kinds_arr)
            x_last = jax.lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)
            x_last = model.normalize(edge_params, x_last)
            logits = model.lm_project(edge_params, x_last)[:, 0]
            res = sample(logits, sp, key, token_counts=counts)
            counts = counts.at[jnp.arange(counts.shape[0]), res.token].add(1)
            return res, kv, counts

        self._hidden_tail = jax.jit(hidden_tail, donate_argnums=(3, 8))

        # full-model paths (prefill/decode_step/decode_chunk): only
        # meaningful when this shard holds every layer, but cheap to build
        # (jit traces lazily) and they make a single-host mesh shard a
        # complete LocalEngine substitute for tests and probes
        def full_logits(window_params, edge_params, tokens, kv, pos, last_idx):
            x = model.embed(edge_params, tokens)
            x, kv = core(window_params, x, kv, pos, last_idx + 1, kinds_arr)
            x_last = jax.lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)
            x_last = model.normalize(edge_params, x_last)
            logits = model.lm_project(edge_params, x_last)
            return logits[:, 0], kv

        self._forward = jax.jit(full_logits, donate_argnums=(3,))

        def decode_and_sample(window_params, edge_params, token, kv, pos, sp, key,
                              counts, plan=None):
            logits, kv = full_logits(window_params, edge_params, token, kv, pos, 0)
            res = sample(logits, sp, key, token_counts=counts, plan=plan)
            counts = counts.at[jnp.arange(counts.shape[0]), res.token].add(1)
            return res, kv, counts

        self._decode = jax.jit(
            decode_and_sample, static_argnums=(8,), donate_argnums=(3, 7)
        )

        def decode_chunk_fn(window_params, edge_params, token, kv, pos, sp, key,
                            counts, n_steps, plan=None):
            def body(carry, _):
                tok, kv, pos, key, counts = carry
                key, step_key = jax.random.split(key)
                logits, kv = full_logits(window_params, edge_params, tok, kv, pos, 0)
                res = sample(logits, sp, step_key, token_counts=counts, plan=plan)
                counts = counts.at[jnp.arange(counts.shape[0]), res.token].add(1)
                return (res.token[:, None], kv, pos + 1, key, counts), res

            (last_tok, kv, _, key, counts), results = jax.lax.scan(
                body, (token, kv, pos, key, counts), None, length=n_steps
            )
            packed = pack_chunk_results(results, plan is None or plan.logprobs)
            return packed, last_tok, kv, key, counts

        self._decode_chunk = jax.jit(
            decode_chunk_fn, static_argnums=(8, 9), donate_argnums=(3, 7)
        )

        L = self.spec_lookahead
        if L > 0:
            # engine-level speculation over the mesh (VERDICT r4 next #5):
            # the shared verify-block body (core/spec.py make_spec_step)
            # with the window pass routed through the shard_map core —
            # drafting/history stay host-shaped, the (L+1)-wide verify
            # forward runs SPMD.  Eligibility gates and the decode_spec
            # driver are inherited unchanged.
            from dnet_tpu.core.spec import make_spec_step

            def window_pass(wp, x, kv, pos, t_real):
                return core(wp, x, kv, pos, jnp.int32(t_real), kinds_arr)

            self._spec_step = jax.jit(
                make_spec_step(model, window_pass, L), donate_argnums=(3, 4)
            )

    # ---- batched lanes over the mesh (r5) ------------------------------
    def place_lane_kv(self, kv):
        """Lane-pool cache placement: [L, slots, S, KVH, Hd] with the same
        axis meanings as the B=1 cache — slots ride the (size-1) dp axis,
        heads shard over tp, sequence over sp."""
        return self._place_kv(kv)

    def build_lane_programs(self, kv_template) -> dict:
        """shard_map(vmap(...)) lane step programs: the per-lane window
        pass (per-lane pos + kv_commit gating) vmaps INSIDE the mesh
        program, so the tp psum seams batch over lanes; head projection +
        per-lane sampling run on the replicated output outside shard_map.
        Signatures match LanePool._build_local exactly — ShardCompute's
        batch-frame hot loop cannot tell the substrates apart."""
        from dnet_tpu.core.sampler import SampleParams
        from dnet_tpu.shard.lanes import lane_sampler

        model, mesh = self.model, self.mesh
        tp_axis = self._tp_axis()
        sp_axis = self._sp_axis()
        certify = self._certify_axes()
        has_kinds = getattr(model, "layer_kinds", None) is not None
        kinds_arr = model.layer_kinds if has_kinds else jnp.zeros((), jnp.int32)
        kvs = self._kv_pspec()
        kv_axes = jax.tree.map(lambda _: 1, kv_template)
        sample_one = lane_sampler(model)
        sp_axes = SampleParams(0, 0, 0, 0, 0, 0, 0, 0)

        def window_lanes(wp, x, kv, pos, active, kinds):
            def one(x_row, kv_row, p, a):
                kv1 = jax.tree.map(lambda t: t[:, None], kv_row)
                xo = pcast_varying(x_row[None], certify)
                xo, kv1 = model.apply_window(
                    wp, xo, kv1, p,
                    layer_kinds=kinds if has_kinds else None,
                    tp_axis=tp_axis, sp_axis=sp_axis, kv_commit=a,
                )
                xo = jax.lax.psum(xo, certify)
                return xo[0], jax.tree.map(lambda t: t[:, 0], kv1)

            return jax.vmap(
                one, in_axes=(0, kv_axes, 0, 0), out_axes=(0, kv_axes)
            )(x, kv, pos, active)

        core = shard_map(
            window_lanes, mesh=mesh,
            in_specs=(self._window_specs, P(), kvs, P(), P(), P()),
            out_specs=(P(), kvs),
        )

        def head(wp, ep, token, kv, pos, active):
            x = model.embed(ep, token)  # [slots, 1, D]
            return core(wp, x, kv, pos, active, kinds_arr)

        def mid(wp, x, kv, pos, active):
            return core(wp, x, kv, pos, active, kinds_arr)

        def tail(wp, ep, x, kv, pos, active, sp, keys, counts):
            x, kv = core(wp, x, kv, pos, active, kinds_arr)
            res, counts, keys = jax.vmap(
                sample_one, in_axes=(None, 0, 0, sp_axes, 0, 0)
            )(ep, x[:, None], active, sp, keys, counts)
            return res, kv, counts, keys

        def full(wp, ep, token, kv, pos, active, sp, keys, counts):
            x = model.embed(ep, token)
            x, kv = core(wp, x, kv, pos, active, kinds_arr)
            res, counts, keys = jax.vmap(
                sample_one, in_axes=(None, 0, 0, sp_axes, 0, 0)
            )(ep, x[:, None], active, sp, keys, counts)
            return res, kv, counts, keys

        return {
            "head": jax.jit(head, donate_argnums=(3,)),
            "mid": jax.jit(mid, donate_argnums=(2,)),
            "tail": jax.jit(tail, donate_argnums=(3, 8)),
            "full": jax.jit(full, donate_argnums=(3, 8)),
        }

    # ---- sessions -----------------------------------------------------
    def new_session(
        self, nonce: str, seed: Optional[int] = None, kv=None, pos: int = 0
    ) -> Session:
        """KV allocates directly with the mesh sharding (heads over tp,
        sequence over sp) so every step reuses the placed buffers in place
        — no per-step resharding.  rotating=False under sp: ring-attention
        shards the sequence axis, which a rotating SWA window would alias."""
        if seed is None:
            seed = int.from_bytes(os.urandom(4), "little")
        kv_list = None
        if kv is None:
            if self.plan.streams_weights:
                # streaming: one mesh-placed cache per layer, matching the
                # per-layer _hidden invocations of _stream_windows
                from dnet_tpu.core.kvcache import init_cache

                kv_list = []
                for _ in self.model.layers:
                    kv0 = init_cache(
                        self.model.kv_config(
                            1, self.batch, self.max_seq, self.kv_dtype,
                            quant_bits=self.kv_quant_bits,
                        )
                    )
                    kv_list.append(self._place_kv(kv0))
            else:
                kv0 = self.model.init_kv(
                    len(self.model.layers), self.batch, self.max_seq,
                    self.kv_dtype, quant_bits=self.kv_quant_bits,
                    rotating=(self.sp == 1),
                )
                kv = self._place_kv(kv0)
        sess = Session(
            nonce=nonce,
            kv=kv,
            kv_list=kv_list,
            pos=pos,
            key=jax.random.key(seed),
            counts=jnp.zeros((self.batch, self.config.vocab_size), dtype=jnp.int32),
            hist=(
                jnp.zeros((self.batch, self.max_seq), dtype=jnp.int32)
                if self.spec_lookahead > 0
                else None
            ),
        )
        self.sessions[nonce] = sess
        return sess
