"""Intra-shard tensor-parallel collectives: the quantizable psum seam.

ROADMAP item 3's TP half: when a ring shard's layer window runs
tensor-parallel over its host-local chips (parallel/tp.py), every layer
pays exactly two collectives — the attention out-proj all-reduce and the
MLP down-proj all-reduce.  The models used to call ``lax.psum`` directly
at those sites; they now route through :func:`tp_all_reduce`, which keeps
the exact psum for plain string axes (every existing mesh program is
byte-identical) and adds an int8 grouped-quantized mode for
:class:`TpAxis`-tagged axes — EQuARX-shaped (arxiv 2506.17615):

    quantize -> all_to_all (scatter chunks) -> dequant + exact local sum
    -> quantize -> all_gather (collect reduced chunks) -> dequant

so the interconnect carries 1-byte codes plus per-group scale/bias pairs
(the PR 14 qsparse8 affine math, compression/ops.py quantize_q8) instead
of 2-4 byte floats, at the cost of two quantization passes of error.
``DNET_TP_COLLECTIVE`` picks the mode: ``lossless`` (exact, the default
resolution on CPU / forced-host meshes so greedy SSE parity holds),
``q8``, or ``auto`` (q8 only on real accelerator meshes).

Everything traced here is pure (DL004): byte accounting and the
collective-latency probe live OUTSIDE the traced functions —
:func:`collective_bytes` is analytic (a pure function of shape/mode), and
engines book it per dispatch via :func:`observe_collective_bytes`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dnet_tpu.utils.jax_compat import axis_size as _axis_size

MODE_LOSSLESS = "lossless"
MODE_Q8 = "q8"
MODE_AUTO = "auto"
TP_COLLECTIVE_MODES = (MODE_AUTO, MODE_LOSSLESS, MODE_Q8)

# f32 scale + f32 bias per quant group (compression/ops.py quantize_q8)
_GROUP_META_BYTES = 8


class TpAxis(str):
    """A mesh axis name carrying its collective mode.

    ``str`` subclass so every existing consumer of an axis name —
    ``lax.psum(x, axis)``, ``axis_size(axis)``, mesh lookups — keeps
    working unchanged; only :func:`tp_all_reduce` / :func:`tp_all_gather`
    look at the extra ``mode``/``group_size`` attributes.  A plain string
    axis means lossless, always.
    """

    mode: str
    group_size: int

    def __new__(
        cls, name: str, mode: str = MODE_LOSSLESS, group_size: int = 64
    ) -> "TpAxis":
        if mode not in (MODE_LOSSLESS, MODE_Q8):
            raise ValueError(
                f"TpAxis mode must be resolved to lossless|q8, got {mode!r} "
                f"(resolve 'auto' via resolve_collective_mode first)"
            )
        if mode == MODE_Q8 and group_size < 1:
            raise ValueError(f"q8 group_size must be >= 1, got {group_size}")
        self = super().__new__(cls, name)
        self.mode = mode
        self.group_size = int(group_size)
        return self


def resolve_collective_mode(mode: str = "", devices=None) -> str:
    """``auto``/empty -> a concrete mode for the given mesh devices.

    q8 only pays off when the collective crosses a real interconnect;
    on CPU (incl. the forced-host test meshes) auto stays lossless so
    greedy SSE streams are byte-identical out of the box — the same
    default-safety contract as the PR 14 ``DNET_WIRE_CODEC=auto`` hop
    resolution (lossy only where DCN is paid).
    """
    if not mode or mode == MODE_AUTO:
        from dnet_tpu.config import get_settings

        cfg_mode = get_settings().tp.tp_collective
        if cfg_mode and cfg_mode != MODE_AUTO:
            mode = cfg_mode
        else:
            devs = list(devices) if devices is not None else jax.devices()
            platform = devs[0].platform if devs else "cpu"
            mode = MODE_Q8 if platform in ("tpu", "gpu") else MODE_LOSSLESS
    if mode not in (MODE_LOSSLESS, MODE_Q8):
        raise ValueError(
            f"unknown TP collective mode {mode!r} "
            f"(expected one of {TP_COLLECTIVE_MODES})"
        )
    return mode


# ---- traced collective bodies (pure; run inside shard_map) ----------------


def _q8_quant_chunks(rows: jnp.ndarray, gs: int):
    """[R, chunk] f32 -> (codes u8 [R, chunk], scale f32 [R, G], bias f32
    [R, G]) with chunk % gs == 0 — the PR 14 qsparse8 affine math."""
    from dnet_tpu.compression.ops import quantize_q8

    return quantize_q8(rows, gs)


def _q8_dequant(codes: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
                gs: int) -> jnp.ndarray:
    """Inverse of _q8_quant_chunks over the last axis, grouped by gs."""
    *lead, K = codes.shape
    G = K // gs
    vals = codes.astype(jnp.float32).reshape(*lead, G, gs)
    vals = vals * scale[..., None] + bias[..., None]
    return vals.reshape(*lead, K)


def _chunk_len(n_elem: int, tp: int, gs: int) -> int:
    """Per-chip chunk length: a multiple of gs covering n_elem / tp."""
    return -(-n_elem // (tp * gs)) * gs


def _q8_all_reduce(x: jnp.ndarray, axis: str, gs: int) -> jnp.ndarray:
    """EQuARX-shaped grouped-int8 all-reduce over ``axis``.

    Phase 1: each chip quantizes its full partial sum once, an all_to_all
    scatters chunk j (codes + per-group scale/bias) to chip j, which
    dequantizes the tp incoming chunks and sums them EXACTLY in f32.
    Phase 2: the reduced chunk re-quantizes once and an all_gather
    collects every chip's chunk.  Two quant passes total, independent of
    tp — not a per-hop requant chain.
    """
    tp = _axis_size(axis)
    if tp == 1:
        return x
    shape = x.shape
    orig_dtype = x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    S = flat.shape[0]
    chunk = _chunk_len(S, tp, gs)
    flat = jnp.pad(flat, (0, tp * chunk - S))
    part = flat.reshape(tp, chunk)  # row j = the chunk chip j will own
    codes, scale, bias = _q8_quant_chunks(part, gs)
    # scatter: after all_to_all, row i holds chip i's partial of MY chunk
    codes = lax.all_to_all(codes, axis, split_axis=0, concat_axis=0)
    scale = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0)
    bias = lax.all_to_all(bias, axis, split_axis=0, concat_axis=0)
    reduced = jnp.sum(_q8_dequant(codes, scale, bias, gs), axis=0)  # [chunk]
    codes1, scale1, bias1 = _q8_quant_chunks(reduced[None], gs)
    codes1 = lax.all_gather(codes1, axis)  # [tp, 1, chunk]
    scale1 = lax.all_gather(scale1, axis)
    bias1 = lax.all_gather(bias1, axis)
    full = _q8_dequant(codes1[:, 0], scale1[:, 0], bias1[:, 0], gs)
    return full.reshape(tp * chunk)[:S].reshape(shape).astype(orig_dtype)


def _q8_all_gather(x: jnp.ndarray, axis: str, gs: int) -> jnp.ndarray:
    """Grouped-int8 all-gather: quantize the local payload once, gather
    codes + scales, dequantize every chip's copy.  Stacks a new leading
    tp axis like ``lax.all_gather``."""
    tp = _axis_size(axis)
    if tp == 1:
        return x[None]
    shape = x.shape
    orig_dtype = x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    S = flat.shape[0]
    K = -(-S // gs) * gs
    flat = jnp.pad(flat, (0, K - S))
    codes, scale, bias = _q8_quant_chunks(flat[None], gs)
    codes = lax.all_gather(codes, axis)  # [tp, 1, K]
    scale = lax.all_gather(scale, axis)
    bias = lax.all_gather(bias, axis)
    full = _q8_dequant(codes[:, 0], scale[:, 0], bias[:, 0], gs)  # [tp, K]
    return full[:, :S].reshape((tp,) + shape).astype(orig_dtype)


def tp_all_reduce(x: jnp.ndarray, axis) -> jnp.ndarray:
    """THE per-layer collective seam: sum partial activations over the
    tensor-parallel mesh axis.

    ``axis`` is a mesh axis name; a plain string (or None) keeps the exact
    ``lax.psum`` every pre-TP mesh program compiled to — byte-identical.
    A :class:`TpAxis` tagged ``q8`` runs the grouped-int8 reduction.
    """
    if axis is None:
        return x
    if isinstance(axis, TpAxis) and axis.mode == MODE_Q8:
        return _q8_all_reduce(x, str(axis), axis.group_size)
    return lax.psum(x, axis)


def tp_all_gather(x: jnp.ndarray, axis) -> jnp.ndarray:
    """Collect per-chip shards over the tp axis (new leading axis).

    Lossless for plain string axes; grouped-int8 payloads for a
    :class:`TpAxis` tagged ``q8``."""
    if axis is None:
        return x[None]
    if isinstance(axis, TpAxis) and axis.mode == MODE_Q8:
        return _q8_all_gather(x, str(axis), axis.group_size)
    return lax.all_gather(x, axis)


# ---- host-side byte accounting + latency probe ----------------------------


def collective_bytes(
    op: str, mode: str, tp: int, n_elem: int, elem_bytes: int,
    group_size: int = 64,
) -> int:
    """Analytic interconnect bytes for ONE collective, summed over the
    mesh (ring-algorithm accounting): what the engines book into
    ``dnet_tp_collective_bytes_total`` per dispatch.  Pure shape math —
    exact for the implementations above, zero device syncs.

    all_reduce lossless: reduce-scatter + all-gather move the tensor
    twice minus the resident share: ``2 * (tp-1) * n * eb``.
    all_reduce q8: phase 1 all_to_all ships (tp-1) quantized chunks per
    chip, phase 2 all-gather forwards each chip's reduced chunk (tp-1)
    times: ``2 * tp * (tp-1) * (chunk + chunk/gs * 8)``.
    all_gather: the per-chip payload forwarded (tp-1) times, lossless
    floats vs int8 codes + group meta.
    """
    if tp <= 1 or n_elem <= 0:
        return 0
    gs = max(int(group_size), 1)
    if op == "all_reduce":
        if mode == MODE_Q8:
            chunk = _chunk_len(n_elem, tp, gs)
            payload = chunk + (chunk // gs) * _GROUP_META_BYTES
            return 2 * tp * (tp - 1) * payload
        return 2 * (tp - 1) * n_elem * elem_bytes
    if op == "all_gather":
        if mode == MODE_Q8:
            padded = -(-n_elem // gs) * gs
            payload = padded + (padded // gs) * _GROUP_META_BYTES
            return tp * (tp - 1) * payload
        return tp * (tp - 1) * n_elem * elem_bytes
    raise ValueError(f"unknown collective op {op!r}")


def observe_collective_bytes(op: str, nbytes: int) -> None:
    """Book one dispatched collective's analytic wire bytes (host side,
    after the launch — never inside traced code)."""
    if nbytes <= 0:
        return
    from dnet_tpu.obs import metric

    metric("dnet_tp_collective_bytes_total").labels(op=op).inc(nbytes)


def probe_collective_ms(
    mesh, axis, hidden: int, dtype, mode: str, group_size: int = 64,
    reps: int = 3,
) -> dict:
    """Load-time collective latency probe: time a standalone jitted
    all_reduce and all_gather of one hidden-frame-shaped tensor on the
    real mesh and observe the medians into ``dnet_tp_collective_ms{op=}``.
    Per-op timing cannot be carved out of the fused layer programs at
    serving time (one XLA computation), so the probe is the honest
    source for this family — the same calibration discipline as
    ``predicted_stage_s`` / ``probe_stage_time``.
    """
    import time

    from dnet_tpu.obs import metric
    from dnet_tpu.obs.jit import instrument_jit
    from dnet_tpu.utils.jax_compat import pcast_varying, shard_map

    from jax.sharding import PartitionSpec as P

    tp_axis = TpAxis(axis, mode=mode, group_size=group_size)

    def reduce_body(v):
        # mark the replicated probe tensor varying so the reduction is
        # legal under the vma checker (identity on 0.4.x)
        return tp_all_reduce(pcast_varying(v, str(tp_axis)), tp_axis)

    def gather_body(v):
        return tp_all_gather(pcast_varying(v, str(tp_axis)), tp_axis)

    spec = P()
    fns = {
        "all_reduce": instrument_jit(
            jax.jit(shard_map(
                reduce_body, mesh=mesh, in_specs=(spec,), out_specs=spec,
            )),
            "tp_collective",
        ),
        "all_gather": instrument_jit(
            jax.jit(shard_map(
                gather_body, mesh=mesh, in_specs=(spec,),
                out_specs=P(None),
            )),
            "tp_collective",
        ),
    }
    x = jnp.ones((1, 1, hidden), dtype=dtype)
    out = {}
    fam = metric("dnet_tp_collective_ms")
    for op, fn in fns.items():
        times = []
        for _ in range(reps + 1):
            t0 = time.perf_counter()
            fn(x).block_until_ready()  # dnetlint: disable=DL005 collective calibration probe: the sync IS the measurement
            times.append((time.perf_counter() - t0) * 1000.0)
        med = sorted(times[1:])[reps // 2]  # drop the compile, take median
        fam.labels(op=op).observe(med)
        out[op] = med
    return out
