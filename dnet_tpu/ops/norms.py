"""Normalization layers (functional, f32 accumulation on the VPU)."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm with float32 accumulation, cast back to x.dtype.

    Matches HF LlamaRMSNorm: y = w * x / sqrt(mean(x^2) + eps).
    """
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)
