"""Flash DECODE attention: split-K Pallas kernel for T=1 against a long cache.

The prefill kernel (ops/flash_attention.py) covers the big-T pass; decode is
the other half of VERDICT r3 weak #6: every generated token attends ONE query
row against the whole preallocated cache, and at 128K context that read IS
the per-token cost.  The dense path (`ops.attention.attend`) pays it badly
three ways: it upcasts the full [S, Hd] K and V to f32, materializes [H, S]
scores + probs through HBM, and — because the cache is preallocated at
max_seq — reads ALL max_seq slots even when only `pos+1` are live.

This kernel streams the cache tile-by-tile with the online-softmax
(m, l, acc) accumulator in VMEM scratch (split-K over the KV axis: the TPU
grid runs KV tiles sequentially with a cross-tile merge, the sequential
sibling of GPU split-K flash-decoding), and uses SCALAR-PREFETCHED block
index maps to clamp dead tiles to the last live tile — Pallas elides the
HBM->VMEM copy when the block index repeats, so a request at pos=2K in a
128K cache reads ~2K slots, not 128K.

Variants (VERDICT r3 next #3):
  - GQA / MLA: all G query heads of a KV group fold per tile; V's head dim
    may differ from K's (deepseek MLA).
  - sinks: gpt_oss per-head sink logits folded once into the denominator.
  - rotating=True: the gpt_oss sliding-window ring buffer — per-slot
    absolute positions are reconstructed in-kernel (slot s holds the most
    recent position <= pos congruent to s mod W) and masked to the window.
  - with_lse: emit UNNORMALIZED (acc, m, l) partials for a cross-rank
    log-sum-exp combine — `sp_flash_decode_attend` composes the kernel with
    the sequence-parallel decode path (ops/ring_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from dnet_tpu.ops.flash_attention import (
    _interpret,
    _pick_tile,
    _under_manual_mesh,
    _vma_union,
)
from dnet_tpu.utils.jax_compat import SDS_HAS_VMA, pcast_varying

NEG_INF = -1e30


def _decode_kernel(scal_ref, q_ref, k_ref, v_ref, *rest,
                   bk: int, scale: float, n_s: int, window: int,
                   rotating: bool, with_lse: bool, qbits: int = 0):
    """One (batch, kv-head, kv-tile) fold of the online softmax.

    scal_ref SMEM [2] = (pos, offset): pos is the query's absolute
    position, offset the absolute position of this cache shard's slot 0
    (nonzero only under sp).  q [G, Hd] is the whole GQA group — one cache
    tile read is amortized over all G query heads sharing it.

    qbits 8/4: the cache tiles arrive QUANTIZED (int8, or int4 nibbles
    packed pairwise along the head dim) with per-(slot, head) f32 scales —
    dequantization happens here in VMEM, so the HBM traffic is the
    quantized bytes, not a full-cache f32 materialization (the read_kv
    dense path's cost)."""
    import jax.experimental.pallas as pl

    if qbits:
        ks_ref, vs_ref, *rest = rest
    if with_lse:
        sink_ref, o_ref, m_out, l_out, m_ref, l_ref, acc_ref = rest
    else:
        sink_ref, o_ref, m_ref, l_ref, acc_ref = rest

    def dequant(ref, scale_ref):
        """[bk, D] f32 from a (possibly quantized) cache tile."""
        t = ref[0, :, 0, :]
        if qbits == 0:
            return t.astype(jnp.float32)
        if qbits == 8:
            return t.astype(jnp.float32) * scale_ref[0, :, 0, :]
        # packed int4: ONE owner of the nibble format (kvcache's unpack is
        # pure jnp + shape-polymorphic, so it lowers inside the kernel too)
        from dnet_tpu.core.kvcache import _unpack_q4

        return _unpack_q4(t) * scale_ref[0, :, 0, :]
    s = pl.program_id(2)
    # full read + static index (not scal_ref[0]): ref indexing discharges
    # to dynamic_slice, which interpret-mode vma tracking rejects when the
    # scalars are device-varying under shard_map (sp partials)
    scal = scal_ref[...]
    pos = scal[0]
    offset = scal[1]

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    W_ring = n_s * bk  # ring-buffer modulus = the cache's slot count
    if rotating:
        live = jnp.minimum(pos + 1, jnp.int32(W_ring))  # live ring slots
    else:
        live = pos + 1 - offset  # local slots this rank may attend
    tile_live = s * bk < live

    @pl.when(tile_live)
    def _fold():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale  # [G, Hd]
        k = dequant(k_ref, ks_ref if qbits else None)  # [bk, Hd]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, bk]
        slot = s * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        if rotating:
            # slot holds the most recent absolute position <= pos congruent
            # to it mod the ring size (written BEFORE attending, so the
            # current token's own slot maps to pos itself); the attention
            # window then masks within the live ring
            k_abs = pos - jnp.mod(pos - slot, jnp.int32(W_ring))
            valid = (k_abs >= 0) & (k_abs > pos - jnp.int32(window))
        else:
            k_abs = offset + slot
            valid = k_abs <= pos
        scores = jnp.where(valid, scores, NEG_INF)

        m_prev = m_ref[:]  # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, dequant(v_ref, vs_ref if qbits else None),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, Vd]
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new

    @pl.when(s == n_s - 1)
    def _emit():
        if with_lse:
            # unnormalized partials: the sp combine folds ranks (and the
            # sink, exactly once) at the global level
            o_ref[0, 0, :, :] = acc_ref[:].astype(o_ref.dtype)
            m_out[0, 0, :] = m_ref[:, 0]
            l_out[0, 0, :] = l_ref[:, 0]
        else:
            sink = sink_ref[0, :][:, None]  # [G, 1]
            m_fin = jnp.maximum(m_ref[:], sink)
            corr = jnp.exp(m_ref[:] - m_fin)
            l_fin = l_ref[:] * corr + jnp.exp(sink - m_fin)
            o_ref[0, 0, :, :] = (
                acc_ref[:] * corr / jnp.maximum(l_fin, 1e-30)
            ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("G", "scale", "bk", "window", "rotating", "with_lse",
                     "interpret", "vma", "qbits", "scal_varying"),
)
def _decode_pallas(q, k, v, scalars, sinks, *, G: int, scale: float, bk: int,
                   window: int, rotating: bool, with_lse: bool,
                   interpret: bool, vma: tuple = (), qbits: int = 0,
                   k_scale=None, v_scale=None, scal_varying: bool = False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, Hd = q.shape
    S = k.shape[1]
    # quantized tiles are narrower in storage (int4 packs pairs); the value
    # head dim that reaches the accumulator is the DEQUANTIZED width
    Vd = v.shape[-1] * (2 if qbits == 4 else 1)
    Hd_k = k.shape[-1]  # stored key width (Hd, or Hd/2 packed)
    Vd_k = v.shape[-1]
    KVH = H // G
    n_s = S // bk

    def live_tile(scal):
        """Last tile holding any live slot (block indices clamp here so the
        pipeline never fetches dead tiles — repeated indices elide copies)."""
        if rotating:
            live = jnp.minimum(scal[0] + 1, jnp.int32(S))
        else:
            live = scal[0] + 1 - scal[1]
        return jnp.clip((live - 1) // bk, 0, n_s - 1)

    def kv_map(b, kh, s, scal):
        return (b, jnp.minimum(s, live_tile(scal)), kh, 0)

    in_specs = [
        pl.BlockSpec((1, 1, G, Hd), lambda b, kh, s, scal: (b, 0, kh, 0)),
        pl.BlockSpec((1, bk, 1, Hd_k), kv_map),
        pl.BlockSpec((1, bk, 1, Vd_k), kv_map),
    ]
    extra_in = ()
    if qbits:
        in_specs += [
            pl.BlockSpec((1, bk, 1, 1), kv_map),  # k_scale
            pl.BlockSpec((1, bk, 1, 1), kv_map),  # v_scale
        ]
        extra_in = (k_scale, v_scale)
    in_specs.append(
        pl.BlockSpec((1, G), lambda b, kh, s, scal: (kh, 0))  # sinks [KVH, G]
    )
    # inside shard_map the partials are device-varying over the sp axis;
    # check_vma demands the output declare it (vma=() outside shard_map)
    kw = {"vma": frozenset(vma)} if (vma and SDS_HAS_VMA) else {}
    out_specs = pl.BlockSpec((1, 1, G, Vd), lambda b, kh, s, scal: (b, 0, kh, 0))
    out_shape = jax.ShapeDtypeStruct((B, T, H, Vd), q.dtype, **kw)
    if with_lse:
        out_specs = (
            out_specs,
            pl.BlockSpec((1, 1, G), lambda b, kh, s, scal: (b, kh, 0)),
            pl.BlockSpec((1, 1, G), lambda b, kh, s, scal: (b, kh, 0)),
        )
        out_shape = (
            jax.ShapeDtypeStruct((B, T, H, Vd), jnp.float32, **kw),
            jax.ShapeDtypeStruct((B, KVH, G), jnp.float32, **kw),
            jax.ShapeDtypeStruct((B, KVH, G), jnp.float32, **kw),
        )
    scratch = [
        pltpu.VMEM((G, 1), jnp.float32),
        pltpu.VMEM((G, 1), jnp.float32),
        pltpu.VMEM((G, Vd), jnp.float32),
    ]
    kernel = functools.partial(
        _decode_kernel, bk=bk, scale=scale, n_s=n_s, window=window,
        rotating=rotating, with_lse=with_lse, qbits=qbits,
    )
    if vma and scal_varying:
        assert qbits == 0, "sp flash decode reads a dequantized shard"
        # sp: the scalars carry a device-varying offset (axis_index), and
        # vma tracking rejects data-dependent block index maps on varying
        # values — drop the dead-tile clamp (each rank's S/sp shard is
        # mostly live under long context) and read the scalars from SMEM
        # instead.  With INVARIANT scalars (tp/mesh-shard decode) the
        # prefetch-grid path below keeps the clamp and just declares the
        # outputs' vma.
        in_specs2 = [
            pl.BlockSpec(memory_space=pltpu.SMEM),  # scalars [2]
            pl.BlockSpec((1, 1, G, Hd), lambda b, kh, s: (b, 0, kh, 0)),
            pl.BlockSpec((1, bk, 1, Hd), lambda b, kh, s: (b, s, kh, 0)),
            pl.BlockSpec((1, bk, 1, Vd), lambda b, kh, s: (b, s, kh, 0)),
            pl.BlockSpec((1, G), lambda b, kh, s: (kh, 0)),
        ]
        out_specs2 = pl.BlockSpec((1, 1, G, Vd), lambda b, kh, s: (b, 0, kh, 0))
        if with_lse:
            out_specs2 = (
                out_specs2,
                pl.BlockSpec((1, 1, G), lambda b, kh, s: (b, kh, 0)),
                pl.BlockSpec((1, 1, G), lambda b, kh, s: (b, kh, 0)),
            )
        return pl.pallas_call(
            kernel, grid=(B, KVH, n_s), in_specs=in_specs2,
            out_specs=out_specs2, out_shape=out_shape,
            scratch_shapes=scratch, interpret=interpret,
        )(scalars, q, k, v, sinks)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, KVH, n_s),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interpret
    )(scalars, q, k, v, *extra_in, sinks)


def _decode_emulate(q, k, v, scalars, sinks, *, G: int, scale: float,
                    bk: int, window: int, rotating: bool, with_lse: bool,
                    qbits: int = 0, k_scale=None, v_scale=None):
    """Plain-jnp twin of _decode_kernel: the SAME tile-by-tile online-
    softmax fold (f32, same operation order, same dead-tile gating), for
    executed coverage where pallas cannot run — interpret mode inside
    shard_map discharges the kernel to a jaxpr whose constants stay
    vma-invariant (r4 diagnosis).  CPU mesh tests, dryruns, and the sp
    composition's interpret path run this emulation; real TPU runs the
    kernel.  Dead tiles are gated exactly like the kernel's `tile_live`
    (an sp rank whose shard lies entirely past `pos` must emit m=NEG_INF,
    l=0 partials, which fold-all would corrupt)."""
    from jax import lax

    B, T, H, _ = q.shape
    S = k.shape[1]
    KVH = H // G
    n_s = S // bk
    Vd = v.shape[-1] * (2 if qbits == 4 else 1)
    pos = scalars[0]
    offset = scalars[1]
    if rotating:
        live = jnp.minimum(pos + 1, jnp.int32(S))
    else:
        live = pos + 1 - offset
    qf = q[:, 0].reshape(B, KVH, G, -1).astype(jnp.float32) * scale

    def dequant(t, sc):
        if qbits == 0:
            return t.astype(jnp.float32)
        if qbits == 8:
            return t.astype(jnp.float32) * sc
        from dnet_tpu.core.kvcache import _unpack_q4

        return _unpack_q4(t) * sc

    def fold(carry, s):
        m, l, acc = carry
        k_t = lax.dynamic_slice_in_dim(k, s * bk, bk, 1)
        v_t = lax.dynamic_slice_in_dim(v, s * bk, bk, 1)
        ks_t = lax.dynamic_slice_in_dim(k_scale, s * bk, bk, 1) if qbits else None
        vs_t = lax.dynamic_slice_in_dim(v_scale, s * bk, bk, 1) if qbits else None
        kf = dequant(k_t, ks_t)  # [B, bk, KVH, Hd]
        vf = dequant(v_t, vs_t)  # [B, bk, KVH, Vd]
        scores = jnp.einsum("bkgd,bskd->bkgs", qf, kf)  # [B, KVH, G, bk]
        slot = s * bk + jnp.arange(bk)
        if rotating:
            k_abs = pos - jnp.mod(pos - slot, jnp.int32(S))
            valid = (k_abs >= 0) & (k_abs > pos - jnp.int32(window))
        else:
            k_abs = offset + slot
            valid = k_abs <= pos
        scores = jnp.where(valid[None, None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum("bkgs,bskd->bkgd", p, vf)
        live_t = s * bk < live
        return (
            jnp.where(live_t, m_new, m),
            jnp.where(live_t, l_new, l),
            jnp.where(live_t, acc_new, acc),
        ), None

    init = (
        jnp.full((B, KVH, G, 1), NEG_INF, jnp.float32),
        jnp.zeros((B, KVH, G, 1), jnp.float32),
        jnp.zeros((B, KVH, G, Vd), jnp.float32),
    )
    # the fold's outputs are varying over the inputs' mesh axes; the scan
    # carry must enter with the same vma (fresh zeros are invariant)
    axes = _vma_union(q, k, v, scalars) or frozenset()
    if axes:
        init = tuple(
            pcast_varying(x, tuple(sorted(axes))) for x in init
        )
    (m, l, acc), _ = lax.scan(fold, init, jnp.arange(n_s))
    if with_lse:
        return (
            acc.reshape(B, 1, H, Vd),
            m[..., 0],
            l[..., 0],
        )
    sink = sinks.astype(jnp.float32).reshape(KVH, G)[None, :, :, None]
    m_fin = jnp.maximum(m, sink)
    corr = jnp.exp(m - m_fin)
    l_fin = l * corr + jnp.exp(sink - m_fin)
    out = acc * corr / jnp.maximum(l_fin, 1e-30)
    return out.reshape(B, 1, H, Vd).astype(q.dtype)


def _shape_ok(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    T, H = q.shape[1], q.shape[2]
    S, KVH = k.shape[1], k.shape[2]
    return T == 1 and H % KVH == 0 and S >= 8 and _pick_tile(S, 256) > 0


def flash_decode_eligible(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    """T=1, GQA-divisible heads, tileable cache length, TPU backend (or the
    DNET_FLASH_INTERPRET test override).  DNET_FLASH_DECODE=0 is the
    operator kill-switch back to the dense decode path.  Inside shard_map
    (mesh ring / mesh-backed shard programs) the kernel runs with explicit
    output vma declarations — or the jnp tile-fold emulation under
    interpret mode; only a broken mesh/vma probe gates to dense (warned
    once in _under_manual_mesh)."""
    from dnet_tpu.config import env_flag

    if not env_flag("DNET_FLASH_DECODE", default=True):
        return False
    if not _interpret() and jax.default_backend() != "tpu":
        return False
    um = _under_manual_mesh()
    if um is None or (um and _vma_union(q, k) is None):
        return False
    return _shape_ok(q, k)


def sp_flash_eligible(q: jnp.ndarray, k_local: jnp.ndarray) -> bool:
    """Eligibility for the sequence-parallel composition, which runs INSIDE
    shard_map by construction: the split-K kernel with declared output vma
    on TPU, the jnp tile-fold emulation under DNET_FLASH_INTERPRET=1 (the
    LSE combine — pmax/psum — is the same code either way, so CPU mesh
    tests execute the composition's algebra)."""
    from dnet_tpu.config import env_flag

    return (
        env_flag("DNET_FLASH_DECODE", default=True)
        and (jax.default_backend() == "tpu" or _interpret())
        and _shape_ok(q, k_local)
    )


def flash_decode_attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    pos,
    scale: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,
    window: int = 0,
    rotating: bool = False,
    offset=None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Single-token decode attention against the (full, preallocated) cache.

    q [B, 1, H, Hd]; k [B, S, KVH, Hd]; v [B, S, KVH, Vd].  Equals the
    dense `attend` with the causal mask at `pos` (linear caches) or the
    rotating sliding-window mask (rotating=True, window=W ring buffers,
    cache written BEFORE the call).  `offset`: absolute position of slot 0
    (sp shards).  With `k_scale`/`v_scale` ([B, S, KVH, 1] f32) the cache
    arrives QUANTIZED — int8, or packed-int4 uint8 with half-width head
    dims — and dequantizes tile-by-tile in VMEM, reading only the
    quantized bytes from HBM (the dense path materializes a full f32
    cache copy through read_kv first).  Caller must check
    flash_decode_eligible."""
    B, T, H, Hd = q.shape
    KVH = k.shape[2]
    G = H // KVH
    scale = Hd**-0.5 if scale is None else scale
    sink_arr = (
        jnp.full((KVH, G), NEG_INF, dtype=jnp.float32)
        if sinks is None
        else sinks.astype(jnp.float32).reshape(KVH, G)
    )
    scalars = jnp.stack(
        [jnp.asarray(pos, jnp.int32),
         jnp.asarray(0 if offset is None else offset, jnp.int32)]
    )
    qbits = 0
    if k_scale is not None:
        qbits = 4 if k.dtype == jnp.uint8 else 8
    if _under_manual_mesh():
        if _interpret():
            return _decode_emulate(
                q, k, v, scalars, sink_arr, G=G, scale=float(scale),
                bk=_pick_tile(k.shape[1], 256), window=int(window),
                rotating=bool(rotating), with_lse=False,
                qbits=qbits, k_scale=k_scale, v_scale=v_scale,
            )
        probe = (q, k, v, scalars, sink_arr) + (
            (k_scale, v_scale) if qbits else ()
        )
        vset = _vma_union(*probe) or frozenset()
        return _decode_pallas(
            q, k, v, scalars, sink_arr, G=G, scale=float(scale),
            bk=_pick_tile(k.shape[1], 256), window=int(window),
            rotating=bool(rotating), with_lse=False, interpret=False,
            qbits=qbits, k_scale=k_scale, v_scale=v_scale,
            vma=tuple(sorted(vset)),
            scal_varying=bool(_vma_union(scalars)),
        )
    return _decode_pallas(
        q, k, v, scalars, sink_arr, G=G, scale=float(scale),
        bk=_pick_tile(k.shape[1], 256), window=int(window),
        rotating=bool(rotating), with_lse=False, interpret=_interpret(),
        qbits=qbits, k_scale=k_scale, v_scale=v_scale,
    )


def sp_flash_decode_attend(
    q: jnp.ndarray,
    k_local: jnp.ndarray,
    v_local: jnp.ndarray,
    pos,
    axis_name: str,
    sinks: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Sequence-parallel flash decode: each rank runs the split-K kernel on
    its KV shard emitting UNNORMALIZED (acc, m, l) partials, then one
    log-sum-exp combine (pmax + 2x psum) merges ranks — the kernel-backed
    twin of `ops.ring_attention.sp_decode_attend` (same collectives, same
    sink algebra, tile reads instead of dense f32 score tensors)."""
    from jax import lax

    B, T, H, Hd = q.shape
    KVH = k_local.shape[2]
    G = H // KVH
    S_local = k_local.shape[1]
    scale = Hd**-0.5 if scale is None else scale
    offset = lax.axis_index(axis_name) * S_local
    scalars = jnp.stack(
        [jnp.asarray(pos, jnp.int32), jnp.asarray(offset, jnp.int32)]
    )
    sink_arr = jnp.full((KVH, G), NEG_INF, dtype=jnp.float32)
    if _interpret():
        # CPU mesh coverage: emulated per-rank partials, REAL collectives —
        # the LSE-combine algebra below executes unchanged
        o, m, l = _decode_emulate(
            q, k_local, v_local, scalars, sink_arr, G=G, scale=float(scale),
            bk=_pick_tile(S_local, 256), window=0, rotating=False,
            with_lse=True,
        )
    else:
        o, m, l = _decode_pallas(
            q, k_local, v_local, scalars, sink_arr, G=G, scale=float(scale),
            bk=_pick_tile(S_local, 256), window=0, rotating=False,
            with_lse=True, interpret=False, vma=(axis_name,),
            scal_varying=True,
        )  # o [B,1,H,Vd] unnormalized f32; m/l [B,KVH,G]
    m_glob = lax.pmax(m, axis_name)
    if sinks is not None:
        sink = sinks.astype(jnp.float32).reshape(KVH, G)[None]
        m_glob = jnp.maximum(m_glob, sink)
    corr = jnp.exp(m - m_glob)  # [B, KVH, G]
    corr_h = corr.reshape(B, 1, H, 1)
    l_glob = lax.psum(l * corr, axis_name)
    o_glob = lax.psum(o * corr_h, axis_name)
    if sinks is not None:
        l_glob = l_glob + jnp.exp(jnp.broadcast_to(sink, m_glob.shape) - m_glob)
    out = o_glob / jnp.maximum(l_glob.reshape(B, 1, H, 1), 1e-30)
    return out.astype(q.dtype)
