"""Ragged paged attention: decode attends the KV block pool IN PLACE.

The paged subsystem (kv/) gave admission and sharing block granularity, but
PR 3 kept the compute dense: every decode tick gathers each slot's page
table into a contiguous per-slot view (`pool[:, ids]` at full width),
steps the existing attention programs over it, and scatters the touched
blocks back.  PR 7's phase attribution prices that round trip exactly —
`dnet_step_phase_ms{phase=kv_gather|kv_scatter}` — and "Ragged Paged
Attention" (PAPERS.md, arxiv 2604.15464) names the TPU-native fix this
module implements: an attention program that consumes the pool-shaped
`[N_blocks, bt, KVH, Hd]` arrays and the `[slots, nb]` int32 page tables
DIRECTLY, so the per-slot view never exists.

The kernel is the split-K online-softmax fold of `ops/flash_decode.py`
with the page table as the scalar-prefetched block index map: grid
(slots, kv_heads, nb) walks each slot's logical blocks, the index map
resolves logical -> physical through the prefetched table, and indices
past a slot's live length clamp to its last live block — Pallas elides
the HBM->VMEM copy when the block index repeats, so a slot at pos=2K in
a 128K-capacity pool reads ~2K slots (the same dead-tile trick, applied
per-sequence instead of per-batch).  Ragged per-slot lengths cost
nothing: length is just each slot's own clamp horizon.  GQA folds all G
query heads of a kv head per tile, and the CURRENT token's k/v row —
not yet in the pool; the block append happens after the launch — is
folded analytically into the (m, l, acc) accumulator at the emit step,
exactly like flash_decode's sink logits.

Three implementations behind one dispatcher (`paged_attend`):

- ``pallas``     — the real kernel (TPU).
- ``interpret``  — the same kernel under pl.pallas_call(interpret=True),
  so CPU tier-1 executes the actual kernel logic incl. the index-map
  clamping (DNET_FLASH_INTERPRET=1, the flash_decode convention).
- ``emulate``    — a plain-jnp twin for backends where interpret mode is
  too slow to serve: gather the table's blocks (already width-bounded by
  the caller's pow2 bucket), write the new row at `pos`, and run the
  shared dense `attend` — the same operation order as the dense-gather
  path, so greedy streams stay byte-identical, fused into the step
  program with no separate gather dispatch and NO scatter at all.

The caller (core/batch.py) owns eligibility via `ragged_refusal`: the
llama-family attention stack (supports_paged_attend), unquantized pool
leaves, and a flat block layout.  Everything else keeps the dense-gather
fallback.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from dnet_tpu.ops.flash_attention import _interpret

NEG_INF = -1e30

#: static implementation choices for the dispatcher (trace-time constant)
PAGED_IMPLS = ("pallas", "interpret", "emulate")


def paged_attend_impl() -> str:
    """Resolve the implementation for this process: the real kernel on
    TPU, the interpret-mode kernel under the DNET_FLASH_INTERPRET test
    override (CPU tier-1 executes the true kernel logic), the jnp twin
    everywhere else (fast enough to SERVE on CPU fallback)."""
    if _interpret():
        return "interpret"
    if jax.default_backend() == "tpu":
        return "pallas"
    return "emulate"


def ragged_refusal(model, kv_quant_bits: int) -> Optional[str]:
    """Why this engine cannot route decode through the ragged program
    (None = eligible).  Mirrors BlockStore's session-layout refusals: the
    dense-gather path stays correct for everything refused here."""
    if not getattr(model, "supports_paged_attend", False):
        return (
            f"{model.config.model_type} attention stack has no paged-attend "
            "hook (non-llama-family layers stay on dense gather)"
        )
    if kv_quant_bits:
        return (
            f"quantized KV cache (bits={kv_quant_bits}) dequantizes through "
            "the dense gather path"
        )
    return None


def _paged_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, kn_ref, vn_ref,
                  o_ref, m_ref, l_ref, acc_ref, *, bt: int, scale: float,
                  nb: int):
    """One (slot, kv-head, logical-block) fold of the online softmax.

    tbl_ref SMEM [slots, nb] page table, pos_ref SMEM [slots] live pool
    rows per slot (the new token's row arrives via kn/vn, folded at emit).
    q [G, Hd] is the slot's whole GQA group for this kv head — one block
    read amortizes over all G query heads sharing it."""
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    i = pl.program_id(2)
    live = pos_ref[b]

    @pl.when(i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(i * bt < live)
    def _fold():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale  # [G, Hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [bt, Hd]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, bt]
        # mid-block ragged edge: the last live block is only partially
        # full — rows at absolute positions >= live are stale pool content
        # (or a clamped repeat of an earlier block) and must not score
        slot = i * bt + jax.lax.broadcasted_iota(jnp.int32, (1, bt), 1)
        scores = jnp.where(slot < live, scores, NEG_INF)

        m_prev = m_ref[:]  # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_ref[0, :, 0, :].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, Vd]
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new

    @pl.when(i == nb - 1)
    def _emit():
        # fold the CURRENT token's row (position == live, always attended
        # under the causal predicate) analytically — it reaches the pool
        # only after the launch, via the kv_append program
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale  # [G, Hd]
        kn = kn_ref[0, 0, 0, :].astype(jnp.float32)  # [Hd]
        vn = vn_ref[0, 0, 0, :].astype(jnp.float32)  # [Vd]
        s_new = jnp.sum(q * kn[None, :], axis=1, keepdims=True)  # [G, 1]
        m_fin = jnp.maximum(m_ref[:], s_new)
        corr = jnp.exp(m_ref[:] - m_fin)
        p_new = jnp.exp(s_new - m_fin)  # [G, 1]
        l_fin = l_ref[:] * corr + p_new
        acc_fin = acc_ref[:] * corr + p_new * vn[None, :]
        o_ref[0, 0, :, :] = (
            acc_fin / jnp.maximum(l_fin, 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("G", "scale", "bt", "interpret"),
)
def _paged_pallas(q, k_pool, v_pool, tables, pos, k_new, v_new, *, G: int,
                  scale: float, bt: int, interpret: bool):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, Hd = q.shape
    KVH = H // G
    Vd = v_pool.shape[-1]
    nb = tables.shape[1]
    qg = q.reshape(B, KVH, G, Hd)
    kn = k_new.reshape(B, KVH, 1, Hd)
    vn = v_new.reshape(B, KVH, 1, Vd)

    def live_block(b, tbl, pos):
        """Last logical block holding any live row for slot b; dead grid
        steps clamp here so the pipeline re-fetches (elides) one block
        instead of streaming unallocated table entries."""
        return jnp.clip((pos[b] - 1) // bt, 0, nb - 1)

    def kv_map(b, kh, i, tbl, pos):
        return (tbl[b, jnp.minimum(i, live_block(b, tbl, pos))], 0, kh, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KVH, nb),
        in_specs=[
            pl.BlockSpec((1, 1, G, Hd), lambda b, kh, i, tbl, pos: (b, kh, 0, 0)),
            pl.BlockSpec((1, bt, 1, Hd), kv_map),
            pl.BlockSpec((1, bt, 1, Vd), kv_map),
            pl.BlockSpec((1, 1, 1, Hd), lambda b, kh, i, tbl, pos: (b, kh, 0, 0)),
            pl.BlockSpec((1, 1, 1, Vd), lambda b, kh, i, tbl, pos: (b, kh, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, G, Vd), lambda b, kh, i, tbl, pos: (b, kh, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, Vd), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, bt=bt, scale=scale, nb=nb)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, Vd), q.dtype),
        interpret=interpret,
    )(tables, pos, qg, k_pool, v_pool, kn, vn)
    return out.reshape(B, T, H, Vd)


def _paged_emulate(q, k_pool, v_pool, tables, pos, k_new, v_new,
                   scale: float):
    """Plain-jnp twin: gather each slot's blocks to a contiguous view
    (width already bounded by the caller's pow2 table bucket), write the
    new row at `pos` exactly like the dense path's write_kv, and attend
    with the causal-at-pos mask through the SAME dense `attend` the
    gather path bottoms out in — one fused program, no separate gather
    dispatch, no scatter.  Serving CPU fallbacks run this; interpret mode
    and TPU run the kernel."""
    from dnet_tpu.ops.attention import attend

    B, T, H, Hd = q.shape
    nb = tables.shape[1]
    bt = k_pool.shape[1]
    KVH = k_pool.shape[2]
    S = nb * bt

    def view(pool):
        g = pool[tables]  # [B, nb, bt, KVH, D]
        return g.reshape(B, S, KVH, pool.shape[-1])

    kc = view(k_pool)
    vc = view(v_pool)
    write = jax.vmap(
        lambda c, r, p: jax.lax.dynamic_update_slice(c, r[None], (p, 0, 0))
    )
    kc = write(kc, k_new.astype(kc.dtype), pos)
    vc = write(vc, v_new.astype(vc.dtype), pos)
    mask = (jnp.arange(S)[None, :] <= pos[:, None])[:, None, :]  # [B, 1, S]
    return attend(q, kc, vc, mask=mask, scale=scale)


def paged_attend(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,
    pos: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    scale: Optional[float] = None,
    impl: str = "emulate",
) -> jnp.ndarray:
    """Single-token decode attention against the block pool, in place.

    q [B, 1, H, Hd]; k_pool/v_pool [N_blocks, bt, KVH, Hd/Vd] (ONE layer's
    pool slices); tables [B, nb] int32 page tables (entries past a slot's
    allocation are 0 — never read thanks to the live clamp); pos [B] int32
    live pool rows per slot; k_new/v_new [B, KVH, Hd/Vd] the current
    token's rows (position == pos, attended in-launch, appended to the
    pool by the caller afterwards).  Equals dense write-then-attend with
    the causal mask at pos.  `impl` is a trace-time constant — callers
    resolve it once via paged_attend_impl()."""
    B, T, H, Hd = q.shape
    KVH = k_pool.shape[2]
    G = H // KVH
    bt = k_pool.shape[1]
    scale = Hd**-0.5 if scale is None else float(scale)
    tables = tables.astype(jnp.int32)
    pos = pos.astype(jnp.int32)
    if impl == "emulate":
        return _paged_emulate(q, k_pool, v_pool, tables, pos, k_new, v_new,
                              scale)
    if impl not in PAGED_IMPLS:
        raise ValueError(f"paged_attend impl {impl!r} not in {PAGED_IMPLS}")
    return _paged_pallas(
        q, k_pool, v_pool, tables, pos, k_new, v_new,
        G=G, scale=scale, bt=bt, interpret=(impl == "interpret"),
    )
