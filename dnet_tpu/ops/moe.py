"""Mixture-of-experts token dispatch: capacity routing + expert parallelism.

The reference computes GPT-OSS MoE experts densely per hosted layer — every
token multiplies every expert's weights and the router's scattered scores
mask the sum (src/dnet/core/models/gpt_oss.py:171-214); it has no expert
parallelism at all (SURVEY.md §2.8: "EP ... absent").  Dense compute wastes
an E/k factor of MXU FLOPs at prefill size.  This module is the TPU-first
redesign: capacity-based token dispatch (GShard/Switch semantics) so each
expert computes only the tokens routed to it, and a true expert-parallel
path where `lax.all_to_all` routes per-expert token buffers between ranks
over ICI.

Three interchangeable compute paths over the same routed-FFN semantics:

- dense       every token x every (local) expert; exact, best for decode-size
              token counts (the models keep this path inline).
- dispatch    scatter tokens into per-expert capacity buffers [E, C, D], run
              the FFN once over the buffers, gather back weighted by the
              router probs.  FLOPs drop from N*E*ffn to E*C*ffn ~= k*cf*N*ffn.
              Tokens routed beyond an expert's capacity are dropped (standard
              MoE capacity semantics); capacity_factor <= 0 selects the exact
              no-drop capacity C = N (tests / small shapes).
- a2a         expert parallelism over a mesh axis: tokens sharded over the
              axis, experts sharded over the same axis.  Each rank scatters
              its token slice into [E, C, D]; `all_to_all` hands each expert
              owner its buffers ([E/R, R*C, D]); local FFN; reverse
              `all_to_all`; local weighted gather.  The hop rides ICI inside
              the jitted program — no wire format, no serialization.

All shapes are static (capacity is a Python int), so every path jits and
scans cleanly.
"""

from __future__ import annotations

import math
from typing import Callable

import jax.numpy as jnp
from jax import lax

from dnet_tpu.utils.jax_compat import axis_size


def expert_capacity(n_tokens: int, n_experts: int, k: int, factor: float) -> int:
    """Per-expert token capacity C (static).  factor <= 0 -> exact (C = n)."""
    if factor <= 0:
        return int(n_tokens)
    c = math.ceil(k * n_tokens * factor / n_experts)
    return max(1, min(int(n_tokens), c))


MOE_IMPLS = ("auto", "dense", "dispatch", "a2a")


def resolve_moe_impl(impl: str, n_tokens: int, n_experts: int, ranks: int) -> str:
    """Pick the compute path for a (token count, expert count, ranks) shape.

    Shapes are static under jit, so this runs at trace time: each padding
    bucket compiles the path that fits it.  Dense wins below ~2E tokens
    (decode); above that dispatch cuts FLOPs by ~E/(k*cf), and with multiple
    expert-sharded ranks the a2a path also shards the dispatch compute.
    """
    if impl not in MOE_IMPLS:
        # fail fast: a typo'd DNET_COMPUTE_MOE_IMPL would otherwise fall
        # through every model branch into silent dense compute
        raise ValueError(f"unknown moe_impl {impl!r}; expected one of {MOE_IMPLS}")
    if impl != "auto":
        return impl
    if n_tokens < max(2 * n_experts, 16):
        return "dense"
    return "a2a" if ranks > 1 else "dispatch"


def route_positions(top_idx: jnp.ndarray, n_experts: int) -> jnp.ndarray:
    """Arrival index of each (token, slot) within its expert's queue.

    top_idx [N, k] int32 expert ids (entries >= n_experts are sentinels and
    get position 0 — callers drop them via the out-of-bounds expert index).
    Returns pos [N, k]: slot-major cumulative count, so a token's place in an
    expert buffer is deterministic in token order.
    """
    flat_e = top_idx.reshape(-1)
    onehot = flat_e[:, None] == jnp.arange(n_experts, dtype=flat_e.dtype)[None, :]
    pos = jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1
    own = jnp.sum(pos * onehot, axis=1)
    return own.reshape(top_idx.shape)


def localize_topk(top_idx: jnp.ndarray, offset, n_local: int) -> jnp.ndarray:
    """Shift global expert ids into a rank's local range; non-local entries
    become the (out-of-bounds) sentinel n_local, so scatter/gather drop them.
    `offset` may be traced (lax.axis_index) — jnp.where keeps it jittable."""
    ok = (top_idx >= offset) & (top_idx < offset + n_local)
    return jnp.where(ok, top_idx - offset, n_local).astype(jnp.int32)


def scatter_to_experts(
    flat: jnp.ndarray, top_idx: jnp.ndarray, pos: jnp.ndarray, n_experts: int, capacity: int
) -> jnp.ndarray:
    """flat [N, D] -> per-expert buffers [E, C, D].  Slots whose expert id or
    queue position is out of bounds (non-local / over capacity) are dropped."""
    vals = jnp.broadcast_to(flat[:, None, :], (*top_idx.shape, flat.shape[-1]))
    buf = jnp.zeros((n_experts, capacity, flat.shape[-1]), flat.dtype)
    return buf.at[top_idx, pos].add(vals, mode="drop")


def gather_from_experts(
    ye: jnp.ndarray, top_idx: jnp.ndarray, pos: jnp.ndarray, top_w: jnp.ndarray
) -> jnp.ndarray:
    """ye [E, C, D] + router weights [N, k] -> combined [N, D]; dropped slots
    contribute zero (mode="fill")."""
    g = ye.at[top_idx, pos].get(mode="fill", fill_value=0)  # [N, k, D]
    return jnp.einsum("nkd,nk->nd", g, top_w.astype(ye.dtype))


def moe_dispatch(
    flat: jnp.ndarray,
    top_idx: jnp.ndarray,
    top_w: jnp.ndarray,
    ffn: Callable[[jnp.ndarray], jnp.ndarray],
    n_experts: int,
    capacity: int,
) -> jnp.ndarray:
    """Single-rank capacity dispatch: [N, D] -> [N, D].

    ffn maps per-expert buffers [E, C, D] -> [E, C, D] (row i uses expert
    i's weights; per-expert biases are added inside, so a dropped token
    simply contributes zero to the combine).
    """
    pos = route_positions(top_idx, n_experts)
    xe = scatter_to_experts(flat, top_idx, pos, n_experts, capacity)
    return gather_from_experts(ffn(xe), top_idx, pos, top_w)


def moe_dispatch_sharded(
    flat: jnp.ndarray,
    top_idx: jnp.ndarray,
    top_w: jnp.ndarray,
    ffn_local: Callable[[jnp.ndarray], jnp.ndarray],
    n_local: int,
    capacity: int,
    axis: str,
) -> jnp.ndarray:
    """Experts sharded over `axis`, tokens replicated: each rank dispatches
    only the slots routed into its expert slice and returns a PARTIAL output
    — the caller psums over `axis` (same seam as the dense path)."""
    offset = lax.axis_index(axis) * n_local
    local_idx = localize_topk(top_idx, offset, n_local)
    pos = route_positions(local_idx, n_local)
    xe = scatter_to_experts(flat, local_idx, pos, n_local, capacity)
    return gather_from_experts(ffn_local(xe), local_idx, pos, top_w)


def moe_apply(
    impl: str,
    flat: jnp.ndarray,
    top_idx: jnp.ndarray,
    top_w: jnp.ndarray,
    ffn_local: Callable[[jnp.ndarray], jnp.ndarray],
    n_local: int,
    capacity_factor: float,
    k: int,
    tp_axis,
    dense_fn: Callable[[], jnp.ndarray],
):
    """One MoE layer through the selected compute path (shared by every MoE
    model; the models supply only their ffn/dense closures and routing).

    Returns (out [N, D], partial): partial=True means the output is a
    per-rank partial sum the caller must psum over tp_axis (the Megatron
    seam both models join their other residual terms at).
    """
    ranks = 1 if tp_axis is None else axis_size(tp_axis)
    n_experts = n_local * ranks  # tp ranks shard the expert dim
    impl = resolve_moe_impl(impl, flat.shape[0], n_experts, ranks)
    if impl == "a2a" and tp_axis is not None:
        out = moe_a2a_replicated(
            flat, top_idx, top_w, ffn_local, n_experts, capacity_factor, k, tp_axis
        )
        return out, False
    if impl in ("dispatch", "a2a"):
        capacity = expert_capacity(flat.shape[0], n_experts, k, capacity_factor)
        if tp_axis is None:
            return moe_dispatch(flat, top_idx, top_w, ffn_local, n_experts, capacity), False
        out = moe_dispatch_sharded(
            flat, top_idx, top_w, ffn_local, n_local, capacity, tp_axis
        )
        return out, True
    return dense_fn(), tp_axis is not None


def moe_a2a_replicated(
    flat: jnp.ndarray,
    top_idx: jnp.ndarray,
    top_w: jnp.ndarray,
    ffn_local: Callable[[jnp.ndarray], jnp.ndarray],
    n_experts: int,
    capacity_factor: float,
    k: int,
    axis: str,
) -> jnp.ndarray:
    """a2a expert parallelism for AXIS-REPLICATED inputs (the Megatron seam
    both MoE models sit behind: x is replicated over the tp axis).

    Splits the token set across ranks (ceil-padded; padded rows carry the
    out-of-bounds sentinel expert id so they dispatch nowhere), runs moe_a2a
    on each rank's slice, and restores replication with a scatter+psum —
    psum output is axis-INVARIANT, so a lax.scan carry through this path
    keeps its axis typing (an all_gather would mark the carry varying).
    Returns the full [N, D] combined output, replicated over `axis`.
    """
    N, D = flat.shape
    R = axis_size(axis)
    n = -(-N // R)
    pad = n * R - N
    if pad:
        flat_p = jnp.pad(flat, ((0, pad), (0, 0)))
        idx_p = jnp.pad(top_idx, ((0, pad), (0, 0)), constant_values=n_experts)
        w_p = jnp.pad(top_w, ((0, pad), (0, 0)))
    else:
        flat_p, idx_p, w_p = flat, top_idx, top_w
    i = lax.axis_index(axis)
    fl = lax.dynamic_slice_in_dim(flat_p, i * n, n)
    ti = lax.dynamic_slice_in_dim(idx_p, i * n, n)
    tw = lax.dynamic_slice_in_dim(w_p, i * n, n)
    C = expert_capacity(n, n_experts, k, capacity_factor)
    out = moe_a2a(fl, ti, tw, ffn_local, n_experts, C, axis)
    buf = jnp.zeros((n * R, out.shape[-1]), out.dtype)
    buf = lax.dynamic_update_slice_in_dim(buf, out, i * n, axis=0)
    return lax.psum(buf, axis)[:N]


def moe_a2a(
    flat: jnp.ndarray,
    top_idx: jnp.ndarray,
    top_w: jnp.ndarray,
    ffn_local: Callable[[jnp.ndarray], jnp.ndarray],
    n_experts: int,
    capacity: int,
    axis: str,
) -> jnp.ndarray:
    """Expert-parallel dispatch over `axis` (R ranks).

    Per rank: flat [n, D] is this rank's token slice, top_idx/top_w [n, k]
    its router output over the GLOBAL expert space, ffn_local computes the
    rank's E/R experts on buffers [E/R, R*C, D].  Capacity is per
    (rank, expert) pair.  Requires n_experts % R == 0.
    """
    pos = route_positions(top_idx, n_experts)
    xe = scatter_to_experts(flat, top_idx, pos, n_experts, capacity)
    # [E, C, D] -> [E/R, R*C, D]: chunk j of the expert axis goes to rank j
    xe = lax.all_to_all(xe, axis, split_axis=0, concat_axis=1, tiled=True)
    ye = ffn_local(xe)
    # [E/R, R*C, D] -> [E, C, D]: return each rank's slice of every buffer
    ye = lax.all_to_all(ye, axis, split_axis=1, concat_axis=0, tiled=True)
    return gather_from_experts(ye, top_idx, pos, top_w)


def swiglu_expert_closures(p, flat, scores, top_idx, top_w, tp_axis):
    """The (effn, dense) closure pair shared by swiglu-expert MoE families
    (mixtral, deepseek's routed experts): p holds stacked {"e_gate",
    "e_up", "e_down"} expert weights, (in, out)-oriented on a leading
    local-expert axis.  effn computes per-expert buffers [E*, C*, D];
    dense() is the exact all-local-experts einsum masked by the scattered
    routing weights, returning this rank's PARTIAL sum under tp (caller
    psums at its residual seam).
    """
    import jax

    from dnet_tpu.ops.quant import dq, lead_dim

    N = flat.shape[0]
    E_local = lead_dim(p["e_gate"])

    def effn(xe):  # per-expert buffers [E*, C*, D] -> [E*, C*, D]
        gate = jnp.einsum("ecd,edf->ecf", xe, dq(p["e_gate"]))
        up = jnp.einsum("ecd,edf->ecf", xe, dq(p["e_up"]))
        return jnp.einsum("ecf,efd->ecd", jax.nn.silu(gate) * up, dq(p["e_down"]))

    def dense():  # scattered weights mask the all-local-experts einsum
        weights = jnp.zeros_like(scores).at[
            jnp.arange(N)[:, None], top_idx
        ].set(top_w)  # [N, E] over the GLOBAL expert space
        gate = jnp.einsum("nd,edf->nef", flat, dq(p["e_gate"]))
        up = jnp.einsum("nd,edf->nef", flat, dq(p["e_up"]))
        inner = jax.nn.silu(gate) * up
        expert_out = jnp.einsum("nef,efd->ned", inner, dq(p["e_down"]))
        if tp_axis is not None:
            e_off = lax.axis_index(tp_axis) * E_local
            w_local = lax.dynamic_slice_in_dim(weights, e_off, E_local, axis=1)
        else:
            w_local = weights
        return jnp.einsum("ned,ne->nd", expert_out, w_local.astype(flat.dtype))

    return effn, dense, E_local
