"""Rotary position embeddings (HF-compatible, incl. Llama-3 scaling).

Frequencies are computed once per model config and closed over by the jitted
step, so inside jit this is two multiplies and an add on the VPU — no tables
in HBM.  Covers the rope variants the reference inherits from mlx-lm's llama/
qwen3 models (reference: src/dnet/core/models/llama.py:106-117 drops HF
`rotary_emb.inv_freq` and recomputes, as we do).
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float = 10000.0,
    scaling: Optional[dict[str, Any]] = None,
    max_position_embeddings: int = 8192,
) -> tuple[np.ndarray, float]:
    """(inv_freq [head_dim//2], attention_scaling) with HF `rope_scaling`.

    attention_scaling multiplies cos/sin (YaRN mscale); 1.0 for other types.
    Matches transformers.modeling_rope_utils for default/linear/llama3/yarn.
    """
    inv_freq = 1.0 / (
        theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim)
    )
    attention_scaling = 1.0
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type", ""))
        if rope_type == "yarn":
            factor = scaling.get("factor", 1.0)
            attention_factor = scaling.get("attention_factor")
            mscale = scaling.get("mscale")
            mscale_all_dim = scaling.get("mscale_all_dim")
            old_len = (
                scaling.get("original_max_position_embeddings")
                or max_position_embeddings
            )

            def get_mscale(scale, ms=1.0):
                if scale <= 1:
                    return 1.0
                return 0.1 * ms * math.log(scale) + 1.0

            if attention_factor is None:
                if mscale and mscale_all_dim:
                    attention_factor = float(
                        get_mscale(factor, mscale) / get_mscale(factor, mscale_all_dim)
                    )
                else:
                    attention_factor = get_mscale(factor)
            attention_scaling = float(attention_factor)

            beta_fast = scaling.get("beta_fast") or 32
            beta_slow = scaling.get("beta_slow") or 1
            dim = head_dim

            def correction_dim(num_rot):
                return (
                    dim * math.log(old_len / (num_rot * 2 * math.pi))
                ) / (2 * math.log(theta))

            low = correction_dim(beta_fast)
            high = correction_dim(beta_slow)
            if scaling.get("truncate", True):
                low, high = math.floor(low), math.ceil(high)
            low, high = max(low, 0), min(high, dim - 1)
            if low == high:
                high += 0.001
            ramp = np.clip(
                (np.arange(dim // 2, dtype=np.float64) - low) / (high - low), 0, 1
            )
            extrapolation_factor = 1 - ramp
            inv_freq = (inv_freq / factor) * (1 - extrapolation_factor) + (
                inv_freq * extrapolation_factor
            )
        elif rope_type == "llama3":
            factor = scaling.get("factor", 8.0)
            low_factor = scaling.get("low_freq_factor", 1.0)
            high_factor = scaling.get("high_freq_factor", 4.0)
            old_len = scaling.get("original_max_position_embeddings", 8192)
            low_wavelen = old_len / low_factor
            high_wavelen = old_len / high_factor
            wavelen = 2 * math.pi / inv_freq
            scaled = np.where(wavelen > low_wavelen, inv_freq / factor, inv_freq)
            smooth = (old_len / wavelen - low_factor) / (high_factor - low_factor)
            mid = (1 - smooth) * inv_freq / factor + smooth * inv_freq
            is_mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
            inv_freq = np.where(is_mid, mid, scaled)
        elif rope_type in ("linear",):
            inv_freq = inv_freq / scaling.get("factor", 1.0)
        # "default"/None: unscaled
    return inv_freq.astype(np.float32), attention_scaling


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
    attention_scaling: float = 1.0,
) -> jnp.ndarray:
    """Rotate q or k.

    x: [B, T, N, head_dim] (head_dim even, half-split convention as in HF).
    positions: [B, T] or [T] absolute token positions.
    attention_scaling: YaRN mscale multiplier on cos/sin.
    """
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., T, D/2]
    if angles.ndim == 2:  # [T, D/2] -> broadcast over batch
        angles = angles[None]
    cos = (jnp.cos(angles) * attention_scaling)[:, :, None, :]  # [B, T, 1, D/2]
    sin = (jnp.sin(angles) * attention_scaling)[:, :, None, :]
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def apply_rope_interleaved(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
    attention_scaling: float = 1.0,
) -> jnp.ndarray:
    """Complex-pair (interleaved) rotary convention: pairs are (x[2i], x[2i+1]).

    DeepSeek-V2's apply_rotary_emb uses view_as_complex, i.e. this layout —
    NOT the half-split convention.  x: [B, T, N, D].
    """
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [.., T, D/2]
    if angles.ndim == 2:
        angles = angles[None]
    cos = (jnp.cos(angles) * attention_scaling)[:, :, None, :]
    sin = (jnp.sin(angles) * attention_scaling)[:, :, None, :]
    xf = x.astype(jnp.float32)
    x_even = xf[..., 0::2]
    x_odd = xf[..., 1::2]
    out_even = x_even * cos - x_odd * sin
    out_odd = x_odd * cos + x_even * sin
    out = jnp.stack([out_even, out_odd], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)
