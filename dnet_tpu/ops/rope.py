"""Rotary position embeddings (HF-compatible, incl. Llama-3 scaling).

Frequencies are computed once per model config and closed over by the jitted
step, so inside jit this is two multiplies and an add on the VPU — no tables
in HBM.  Covers the rope variants the reference inherits from mlx-lm's llama/
qwen3 models (reference: src/dnet/core/models/llama.py:106-117 drops HF
`rotary_emb.inv_freq` and recomputes, as we do).
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float = 10000.0,
    scaling: Optional[dict[str, Any]] = None,
) -> np.ndarray:
    """inv_freq [head_dim//2] with optional HF `rope_scaling` applied."""
    inv_freq = 1.0 / (
        theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim)
    )
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type", ""))
        if rope_type == "llama3":
            factor = scaling.get("factor", 8.0)
            low_factor = scaling.get("low_freq_factor", 1.0)
            high_factor = scaling.get("high_freq_factor", 4.0)
            old_len = scaling.get("original_max_position_embeddings", 8192)
            low_wavelen = old_len / low_factor
            high_wavelen = old_len / high_factor
            wavelen = 2 * math.pi / inv_freq
            scaled = np.where(wavelen > low_wavelen, inv_freq / factor, inv_freq)
            smooth = (old_len / wavelen - low_factor) / (high_factor - low_factor)
            mid = (1 - smooth) * inv_freq / factor + smooth * inv_freq
            is_mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
            inv_freq = np.where(is_mid, mid, scaled)
        elif rope_type in ("linear",):
            inv_freq = inv_freq / scaling.get("factor", 1.0)
        # "default"/None: unscaled
    return inv_freq.astype(np.float32)


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate q or k.

    x: [B, T, N, head_dim] (head_dim even, half-split convention as in HF).
    positions: [B, T] or [T] absolute token positions.
    """
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., T, D/2]
    if angles.ndim == 2:  # [T, D/2] -> broadcast over batch
        angles = angles[None]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, T, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
