"""Weight-only int8 quantization (per-group symmetric, fused dequant).

Decode is HBM-bandwidth-bound: every token reads every weight.  int8 weights
halve the bytes per token (~2x decode roofline); the dequant (convert +
multiply by per-group scales) fuses into the consuming matmul's operand
load on TPU, so no full-precision copy is ever materialized.

Layout: a quantized weight is {"q": int8 [..., in, out], "s": bf16
[..., in/G, out]} with groups along the IN (contraction) dimension.
`dq()` is the universal accessor — it passes plain arrays through, so model
code is quantization-agnostic.
"""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp
import numpy as np

DEFAULT_GROUP = 128


def quantize_weight_q8(
    w: np.ndarray, group_size: int = DEFAULT_GROUP, scale_dtype=None
) -> dict:
    """[..., in, out] float -> {"q": int8, "s": scales} grouped along in.

    Scales carry the serving precision: `dq` dequantizes to their dtype."""
    w = np.asarray(w)
    *lead, inn, out = w.shape
    if inn % group_size != 0:
        # fall back to one group per whole axis when it doesn't tile
        group_size = inn
    g = inn // group_size
    wf = w.astype(np.float32).reshape(*lead, g, group_size, out)
    amax = np.abs(wf).max(axis=-2, keepdims=True)  # [..., g, 1, out]
    scale = np.maximum(amax / 127.0, 1e-12)
    q = np.clip(np.round(wf / scale), -127, 127).astype(np.int8)
    if scale_dtype is None:
        import ml_dtypes

        scale_dtype = ml_dtypes.bfloat16
    return {
        "q": q.reshape(*lead, inn, out),
        "s": scale.squeeze(-2).astype(scale_dtype),  # [..., g, out]
    }


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def out_dim(w) -> int:
    """Output (last-axis) dimension of a maybe-quantized weight."""
    return (w["q"] if is_quantized(w) else w).shape[-1]


def lead_dim(w) -> int:
    """Leading-axis dimension of a maybe-quantized weight (e.g. local expert
    count of a stacked MoE weight)."""
    return (w["q"] if is_quantized(w) else w).shape[0]


def dq(w: Union[jnp.ndarray, dict], dtype=None) -> jnp.ndarray:
    """Dequantize-or-passthrough.  XLA fuses this into the consuming matmul.

    Default target dtype is the scales' dtype (set at quantize time from the
    engine's param_dtype), so float32 serving is not silently downgraded."""
    if not is_quantized(w):
        return w
    q, s = w["q"], w["s"]
    if dtype is None:
        dtype = s.dtype
    *lead, inn, out = q.shape
    g = s.shape[-2]
    group = inn // g
    deq = q.astype(dtype).reshape(*lead, g, group, out) * s.astype(dtype)[..., :, None, :]
    return deq.reshape(*lead, inn, out)


def quantize_tree(
    params: dict, keys: set, group_size: int = DEFAULT_GROUP, scale_dtype=None
) -> dict:
    """Quantize the named 2D+ weights in a (stacked) param dict."""
    out = {}
    for k, v in params.items():
        if k in keys and not is_quantized(v) and np.asarray(v).ndim >= 2:
            out[k] = quantize_weight_q8(np.asarray(v), group_size, scale_dtype)
        else:
            out[k] = v
    return out


# weights worth quantizing (the big matmuls; norms/biases/sinks stay float)
QUANTIZABLE = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",  # llama/qwen3
    "gate_up", "down",  # gpt_oss experts
}
