"""Weight-only int8/int4 quantization (per-group symmetric, fused dequant).

Decode is HBM-bandwidth-bound: every token reads every weight.  int8 weights
halve the bytes per token (~2x decode roofline), int4 quarters them (~4x);
the dequant (convert + multiply by per-group scales) fuses into the
consuming matmul's operand load on TPU, so no full-precision copy is ever
materialized.  int4 matches the reference's dominant serving envelope
(4-bit catalog entries, src/dnet/api/catalog.py).

Layouts (groups along the IN / contraction dimension):
- int8: {"q": int8 [..., in, out], "s": [..., in/G, out]}
- int4: {"q4": uint8 [..., in/2, out], "s": [..., in/G, out]} — two
  offset-binary nibbles per byte, adjacent in-rows share a byte (even row
  in the low nibble).
`dq()` is the universal accessor — it passes plain arrays through, so model
code is quantization-agnostic.  Scales carry the serving precision.
"""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp
import numpy as np

DEFAULT_GROUP = 128
DEFAULT_GROUP_Q4 = 64  # int4 needs finer groups for acceptable error


def quantize_weight_q8(
    w: np.ndarray, group_size: int = DEFAULT_GROUP, scale_dtype=None
) -> dict:
    """[..., in, out] float -> {"q": int8, "s": scales} grouped along in.

    Scales carry the serving precision: `dq` dequantizes to their dtype."""
    w = np.asarray(w)
    *lead, inn, out = w.shape
    if inn % group_size != 0:
        # fall back to one group per whole axis when it doesn't tile
        group_size = inn
    g = inn // group_size
    wf = w.astype(np.float32).reshape(*lead, g, group_size, out)
    amax = np.abs(wf).max(axis=-2, keepdims=True)  # [..., g, 1, out]
    scale = np.maximum(amax / 127.0, 1e-12)
    q = np.clip(np.round(wf / scale), -127, 127).astype(np.int8)
    if scale_dtype is None:
        import ml_dtypes

        scale_dtype = ml_dtypes.bfloat16
    return {
        "q": q.reshape(*lead, inn, out),
        "s": scale.squeeze(-2).astype(scale_dtype),  # [..., g, out]
    }


def quantize_weight_q4(
    w: np.ndarray, group_size: int = DEFAULT_GROUP_Q4, scale_dtype=None
) -> dict:
    """[..., in, out] float -> {"q4": packed uint8, "s": scales}.

    Symmetric [-7, 7] stored offset-binary (value + 8), two nibbles per
    byte along the in axis.  Requires an even in dim."""
    w = np.asarray(w)
    *lead, inn, out = w.shape
    if inn % 2 != 0:
        raise ValueError(f"int4 packing needs an even contraction dim, got {inn}")
    if group_size % 2 != 0:
        raise ValueError(f"int4 group_size must be even, got {group_size}")
    if inn % group_size != 0:
        group_size = inn  # one group per whole axis when it doesn't tile
    g = inn // group_size
    wf = w.astype(np.float32).reshape(*lead, g, group_size, out)
    amax = np.abs(wf).max(axis=-2, keepdims=True)
    scale = np.maximum(amax / 7.0, 1e-12)
    q = (np.clip(np.round(wf / scale), -7, 7) + 8).astype(np.uint8)
    q = q.reshape(*lead, inn, out)
    packed = q[..., 0::2, :] | (q[..., 1::2, :] << 4)  # [..., in/2, out]
    if scale_dtype is None:
        import ml_dtypes

        scale_dtype = ml_dtypes.bfloat16
    return {"q4": packed, "s": scale.squeeze(-2).astype(scale_dtype)}


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "s" in w and ("q" in w or "q4" in w)


def _qarr(w: dict) -> "np.ndarray":
    return w["q"] if "q" in w else w["q4"]


def out_dim(w) -> int:
    """Output (last-axis) dimension of a maybe-quantized weight."""
    return (_qarr(w) if is_quantized(w) else w).shape[-1]


def lead_dim(w) -> int:
    """Leading-axis dimension of a maybe-quantized weight (e.g. local expert
    count of a stacked MoE weight)."""
    return (_qarr(w) if is_quantized(w) else w).shape[0]


def dq(w: Union[jnp.ndarray, dict], dtype=None) -> jnp.ndarray:
    """Dequantize-or-passthrough.  XLA fuses this into the consuming matmul.

    Default target dtype is the scales' dtype (set at quantize time from the
    engine's param_dtype), so float32 serving is not silently downgraded."""
    if not is_quantized(w):
        return w
    s = w["s"]
    if dtype is None:
        dtype = s.dtype
    if "q4" in w:
        p = w["q4"]
        *lead, half, out = p.shape
        inn = half * 2
        lo = (p & jnp.uint8(0xF)).astype(dtype) - 8.0
        hi = ((p >> 4) & jnp.uint8(0xF)).astype(dtype) - 8.0
        # re-interleave: even in-rows came from the low nibble
        q = jnp.stack([lo, hi], axis=-2).reshape(*lead, inn, out)
    else:
        q = w["q"].astype(dtype)
        *lead, inn, out = q.shape
    g = s.shape[-2]
    group = inn // g
    deq = q.reshape(*lead, g, group, out) * s.astype(dtype)[..., :, None, :]
    return deq.reshape(*lead, inn, out)


def embed_lookup(w: Union[jnp.ndarray, dict], tokens: jnp.ndarray) -> jnp.ndarray:
    """Row gather from a maybe-quantized embedding table.

    Plain: w [vocab, hidden] -> w[tokens] ([..., hidden]).
    Quantized: w holds the PROJECTION layout ({"q"/"q4": [hidden(/2), vocab],
    "s": [g, vocab]} — see RingModel.quantize_edge), so logical table rows
    are physical columns: gather per-token columns, then dequantize with the
    per-group scales of those tokens.  Reads O(tokens * hidden) bytes either
    way — quantizing the table costs the lookup nothing while halving/
    quartering the O(hidden * vocab) projection read."""
    if not is_quantized(w):
        return w[tokens]
    tok = jnp.asarray(tokens)
    s = w["s"]
    dtype = s.dtype
    sg = s[:, tok].astype(dtype)  # [g, *tok]
    if "q4" in w:
        p = w["q4"][:, tok]  # [hidden/2, *tok]
        lo = (p & jnp.uint8(0xF)).astype(dtype) - 8.0
        hi = ((p >> 4) & jnp.uint8(0xF)).astype(dtype) - 8.0
        # even hidden rows came from the low nibble (see quantize_weight_q4)
        q = jnp.stack([lo, hi], axis=1).reshape(-1, *tok.shape)
    else:
        q = w["q"][:, tok].astype(dtype)  # [hidden, *tok]
    hidden = q.shape[0]
    g = sg.shape[0]
    deq = q.reshape(g, hidden // g, *tok.shape) * sg[:, None]
    return jnp.moveaxis(deq.reshape(hidden, *tok.shape), 0, -1)


def quantize_tree(
    params: dict,
    keys: set,
    group_size: int = 0,
    scale_dtype=None,
    bits: int = 8,
) -> dict:
    """Quantize the named 2D+ weights in a (stacked) param dict."""
    if bits not in (4, 8):
        raise NotImplementedError(f"weight quantization bits={bits} (4 or 8)")
    quantize = quantize_weight_q4 if bits == 4 else quantize_weight_q8
    group_size = group_size or (DEFAULT_GROUP_Q4 if bits == 4 else DEFAULT_GROUP)
    out = {}
    for k, v in params.items():
        if k in keys and not is_quantized(v) and np.asarray(v).ndim >= 2:
            out[k] = quantize(np.asarray(v), group_size, scale_dtype)
        else:
            out[k] = v
    return out


# weights worth quantizing (the big matmuls; norms/biases/sinks stay float)
QUANTIZABLE = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",  # llama/qwen3
    "gate_up", "down",  # gpt_oss experts
}
