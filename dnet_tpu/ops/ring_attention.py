"""Ring attention / sequence-parallel attention over a mesh axis.

The reference lists ">128K context" as unshipped roadmap (README.md:51,
SURVEY.md §2.8); on TPU this is a first-class design axis: shard the KV
sequence over the `sp` mesh axis and

- prefill: rotate KV blocks around the ring with `lax.ppermute`, folding
  each visiting block into an online-softmax accumulator (flash-attention
  combine) — O(S/sp) memory per chip, full-S attention, ICI-bandwidth hops
  (Ring Attention, Liu et al. 2023);
- decode: the single query is replicated; every rank computes a partial
  (m, l, o) against its local KV block and one log-sum-exp combine
  (pmax + psum) merges them — distributed flash-decoding.

Both are numerically exact vs dense attention (tests compare against
ops.attention.attend).  GQA layout matches attend(): q [B,T,H,Hd],
k/v [B,S_local,KVH,Hd].
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dnet_tpu.utils.jax_compat import pcast_varying

NEG = -1e30


def _block_scores(q5, k, mask):
    """q5: [B,KVH,G,Tq,Hd] scaled f32; k: [B,S,KVH,Hd] -> [B,KVH,G,Tq,S]."""
    scores = jnp.einsum("bkgtd,bskd->bkgts", q5, k.astype(jnp.float32))
    if mask is not None:
        scores = jnp.where(mask[None, None, None], scores, NEG)
    return scores


def _fold_block(q5, k, v, mask, m, l, o):
    """Online-softmax fold of one KV block into the (m, l, o) accumulator."""
    scores = _block_scores(q5, k, mask)  # [B,KVH,G,Tq,S]
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bkgts,bskd->bkgtd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, o_new


def ring_attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Full ring attention inside shard_map: q is THIS rank's query block,
    k/v THIS rank's KV block; blocks rotate `sp` times around the axis.

    q_positions [Tq], kv_positions [S_local]: absolute token positions
    (rotate with the KV so causal masking stays correct).
    `scale` overrides the Hd**-0.5 softmax scale (MLA YaRN mscale).
    Returns [B, Tq, H, Hd] in q.dtype.
    """
    SP = lax.psum(1, axis_name)
    B, Tq, H, Hd = q.shape
    KVH = k.shape[2]
    G = H // KVH
    q5 = (q.reshape(B, Tq, KVH, G, Hd).transpose(0, 2, 3, 1, 4).astype(jnp.float32)
          * (Hd**-0.5 if scale is None else scale))  # [B,KVH,G,Tq,Hd]

    # accumulators become device-varying over the axis once folded with the
    # rank-local KV; mark them so the fori carry types line up
    m = pcast_varying(jnp.full((B, KVH, G, Tq), NEG, dtype=jnp.float32), axis_name)
    l = pcast_varying(jnp.zeros((B, KVH, G, Tq), dtype=jnp.float32), axis_name)
    o = pcast_varying(jnp.zeros((B, KVH, G, Tq, Hd), dtype=jnp.float32), axis_name)

    perm = [(r, (r + 1) % SP) for r in range(SP)]

    def body(_, carry):
        k, v, kv_pos, m, l, o = carry
        mask = (
            kv_pos[None, :] <= q_positions[:, None] if causal else None
        )  # [Tq, S_local]
        m, l, o = _fold_block(q5, k, v, mask, m, l, o)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        kv_pos = lax.ppermute(kv_pos, axis_name, perm)
        return k, v, kv_pos, m, l, o

    k, v, kv_pos, m, l, o = lax.fori_loop(
        0, SP, body, (k, v, kv_positions, m, l, o)
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Tq, H, Hd).astype(q.dtype)


def sp_decode_attend(
    q: jnp.ndarray,
    k_local: jnp.ndarray,
    v_local: jnp.ndarray,
    valid_local: jnp.ndarray,
    axis_name: str,
    sinks: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Distributed flash-decoding: q [B,T,H,Hd] replicated over the axis,
    k/v [B,S_local,KVH,Hd] this rank's KV shard, valid_local [T, S_local]
    boolean attendability mask (causal + written-slot validity).

    One cross-device LSE combine (pmax + 2x psum) merges the partials.
    sinks [H]: GPT-OSS attention-sink logits — a virtual key absorbing
    probability mass, folded into the global softmax denominator exactly
    once (outside the psum).  `scale` overrides the Hd**-0.5 softmax scale
    (MLA YaRN mscale compensation must survive the sp path).
    """
    B, Tq, H, Hd = q.shape
    KVH = k_local.shape[2]
    G = H // KVH
    q5 = (q.reshape(B, Tq, KVH, G, Hd).transpose(0, 2, 3, 1, 4).astype(jnp.float32)
          * (Hd**-0.5 if scale is None else scale))

    scores = _block_scores(q5, k_local, valid_local)
    m_loc = jnp.max(scores, axis=-1)  # [B,KVH,G,Tq]
    m_glob = lax.pmax(m_loc, axis_name)
    if sinks is not None:
        sink = sinks.astype(jnp.float32).reshape(KVH, G)[None, :, :, None]
        m_glob = jnp.maximum(m_glob, sink)
    p = jnp.exp(scores - m_glob[..., None])
    l_loc = jnp.sum(p, axis=-1)
    o_loc = jnp.einsum("bkgts,bskd->bkgtd", p, v_local.astype(jnp.float32))
    l_glob = lax.psum(l_loc, axis_name)
    o_glob = lax.psum(o_loc, axis_name)
    if sinks is not None:
        l_glob = l_glob + jnp.exp(jnp.broadcast_to(sink, m_glob.shape) - m_glob)
    out = o_glob / jnp.maximum(l_glob[..., None], 1e-30)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Tq, H, v_local.shape[-1]).astype(q.dtype)
