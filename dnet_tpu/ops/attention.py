"""Attention ops: GQA scaled-dot-product with causal / sliding-window masks.

Pure-XLA reference path (einsum + f32 softmax — XLA fuses the mask and
softmax into the matmuls on TPU); a Pallas flash kernel can swap in behind
`attend` without touching callers.  Covers what the reference gets from
mlx-lm's `scaled_dot_product_attention` plus the GPT-OSS-style dual
full/sliding masks (reference: src/dnet/core/models/gpt_oss.py:111-170).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-negative instead of -inf: keeps softmax NaN-free on fully-masked rows


def causal_mask(q_len: int, kv_len: int, q_offset) -> jnp.ndarray:
    """[q_len, kv_len] boolean mask; True = attend.

    q_offset: absolute position of the first query (traced or static).
    Query i (absolute q_offset+i) may attend keys at absolute positions
    <= q_offset+i.
    """
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    kv_pos = jnp.arange(kv_len)[None, :]
    return kv_pos <= q_pos


def sliding_window_mask(q_len: int, kv_len: int, q_offset, window: int) -> jnp.ndarray:
    """Causal mask further restricted to the last `window` keys."""
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    kv_pos = jnp.arange(kv_len)[None, :]
    return (kv_pos <= q_pos) & (kv_pos > q_pos - window)


def sp_causal_mask(q_len: int, kv_local: int, q_offset, sp_axis: str) -> jnp.ndarray:
    """Causal mask against THIS rank's KV shard (sequence axis sharded over
    `sp_axis`): causality is computed on absolute slot positions."""
    offset = lax.axis_index(sp_axis) * kv_local
    kv_pos = offset + jnp.arange(kv_local)[None, :]
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    return kv_pos <= q_pos


def sp_sliding_window_mask(
    q_len: int, kv_local: int, q_offset, window: int, sp_axis: str
) -> jnp.ndarray:
    """Sliding-window causal mask against this rank's KV shard."""
    offset = lax.axis_index(sp_axis) * kv_local
    kv_pos = offset + jnp.arange(kv_local)[None, :]
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    return (kv_pos <= q_pos) & (kv_pos > q_pos - window)


def cached_attend(
    q: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    kvs: dict,
    pos,
    mask: Optional[jnp.ndarray],
    kv_commit=None,
    sp_axis: Optional[str] = None,
    sinks: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    causal: bool = False,
) -> Tuple[jnp.ndarray, dict]:
    """Write the new k/v into one layer's cache slices and attend over the
    full cache — the shared body of every model's attention block.  With
    `sp_axis` the cache holds this rank's sequence shard and attention runs
    as distributed flash-decoding (`mask` must then be rank-local, e.g.
    sp_causal_mask).  `causal=True` (mask ignored) declares the standard
    prefill predicate — row i attends slots [0, pos+i] — unlocking the
    Pallas flash kernel on TPU (O(T x Hd) memory instead of the dense
    [.., T, S] score tensor; ops/flash_attention.py)."""
    from dnet_tpu.core.kvcache import read_kv, write_kv, write_kv_sp
    from dnet_tpu.ops.ring_attention import sp_decode_attend

    if causal:
        # the flag REPLACES the mask; a caller combining both would get
        # full-causal attention instead of its restrictive mask
        assert mask is None, "cached_attend: causal=True requires mask=None"
    if sp_axis is None:
        kvs = write_kv(kvs, k_new, v_new, pos, kv_commit)
        if causal and q.shape[1] == 1 and "k_scale" in kvs:
            # quantized decode: dequantize tile-by-tile INSIDE the split-K
            # kernel — read_kv would first materialize a full f32 cache copy
            # through HBM, erasing the quantization's bandwidth win
            from dnet_tpu.ops.flash_decode import (
                flash_decode_attend,
                flash_decode_eligible,
            )

            if flash_decode_eligible(q, kvs["k"]):
                return (
                    flash_decode_attend(
                        q, kvs["k"], kvs["v"], pos, scale=scale, sinks=sinks,
                        k_scale=kvs["k_scale"], v_scale=kvs["v_scale"],
                    ),
                    kvs,
                )
        kc, vc = read_kv(kvs)
        if causal:
            from dnet_tpu.ops.flash_attention import flash_attend_causal

            return flash_attend_causal(q, kc, vc, pos, scale=scale, sinks=sinks), kvs
        return attend(q, kc, vc, mask=mask, sinks=sinks, scale=scale), kvs
    kvs = write_kv_sp(kvs, k_new, v_new, pos, sp_axis, kv_commit)
    kc, vc = read_kv(kvs)
    if causal:
        # sp decode with the plain causal predicate: the split-K Pallas
        # kernel computes per-rank (acc, m, l) partials before the LSE
        # combine — on TPU as the real kernel (declared output vma), under
        # DNET_FLASH_INTERPRET=1 as the jnp tile-fold emulation (pallas
        # interpret inside shard_map is broken; ops/flash_decode.py), with
        # the dense distributed flash-decoding everywhere else.
        from dnet_tpu.ops.flash_decode import (
            sp_flash_decode_attend,
            sp_flash_eligible,
        )

        if sp_flash_eligible(q, kc):
            return (
                sp_flash_decode_attend(
                    q, kc, vc, pos, sp_axis, sinks=sinks, scale=scale
                ),
                kvs,
            )
        mask = sp_causal_mask(q.shape[1], kc.shape[1], pos, sp_axis)
    return sp_decode_attend(q, kc, vc, mask, sp_axis, sinks=sinks, scale=scale), kvs


def rotating_cached_attend(
    q: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    kvs: dict,
    pos,
    window: int,
    kv_commit=None,
    sinks: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    t_real=None,
) -> Tuple[jnp.ndarray, dict]:
    """Sliding-window attention over an O(window) ring-buffer cache.

    The cache holds only the last `window` tokens (slot = pos % window), so
    a 128K-context SWA layer stores W rows instead of S_max — the memory
    saving the reference gets from mlx's RotatingKVCache
    (src/dnet/core/models/gpt_oss.py:291-303).  Queries attend the PREVIOUS
    window from the cache plus the in-chunk keys directly (a chunk longer
    than the window would otherwise overwrite keys its own earlier queries
    need), with masks built from each slot's absolute position."""
    from dnet_tpu.core.kvcache import read_kv, write_kv_rotating

    T = q.shape[1]
    W = kvs["k"].shape[1]
    if T == 1 and kv_commit is None:
        # SWA decode through the split-K kernel: write the ring FIRST, then
        # attend the whole buffer with per-slot absolute positions
        # reconstructed in-kernel (slot s holds the latest position <= pos
        # congruent to s mod W).  Gated off under kv_commit: the dense path
        # attends the new key even on non-committing pipeline ranks, and the
        # kernel reads only the (unwritten) cache.
        from dnet_tpu.ops.flash_decode import (
            flash_decode_attend,
            flash_decode_eligible,
        )

        if flash_decode_eligible(q, kvs["k"]):
            kvs = write_kv_rotating(kvs, k_new, v_new, pos, None, t_real=t_real)
            # quantized rings pass raw tiles + scales (dequant in-kernel);
            # a None k_scale selects the unquantized kernel path
            if "k_scale" in kvs:
                kc, vc = kvs["k"], kvs["v"]
            else:
                kc, vc = read_kv(kvs)
            attn = flash_decode_attend(
                q, kc, vc, pos, scale=scale, sinks=sinks, window=window,
                rotating=True, k_scale=kvs.get("k_scale"),
                v_scale=kvs.get("v_scale"),
            )
            return attn, kvs
    k_prev, v_prev = read_kv(kvs)  # [B, W, KVH, Hd]
    keys = jnp.concatenate([k_prev, k_new.astype(k_prev.dtype)], axis=1)
    vals = jnp.concatenate([v_prev, v_new.astype(v_prev.dtype)], axis=1)

    i = jnp.arange(T)[:, None]
    p_abs = pos + i  # absolute query positions [T, 1]
    s = jnp.arange(W)[None, :]
    # slot s holds the most recent pre-chunk position congruent to s mod W
    a_prev = (pos - 1) - jnp.mod(pos - 1 - s, W)
    m_prev = (a_prev >= 0) & (a_prev > p_abs - window)
    j = jnp.arange(T)[None, :]  # in-chunk key index
    m_new = (j <= i) & (j > i - window)
    if t_real is not None:
        # bucket padding: padded keys are not real context, and their
        # positions must never wrap into the ring (they would destroy the
        # live rows a later decode still reads)
        m_new = m_new & (j < t_real)
    mask = jnp.concatenate([m_prev, m_new], axis=1)  # [T, W+T]

    attn = attend(q, keys, vals, mask=mask, sinks=sinks, scale=scale)
    kvs = write_kv_rotating(kvs, k_new, v_new, pos, kv_commit, t_real=t_real)
    return attn, kvs


def attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Grouped-query attention.

    q: [B, T, H, Hd];  k, v: [B, S, KVH, Hd] with H % KVH == 0.
    mask: broadcastable to [B, T, S] or [T, S]; True = attend.
    sinks: optional per-head attention-sink logits [H] (GPT-OSS style): a
      virtual key that absorbs probability mass but contributes no value.
    Returns [B, T, H, Hd] in q.dtype (softmax in f32).
    """
    B, T, H, Hd = q.shape
    S, KVH = k.shape[1], k.shape[2]
    G = H // KVH
    scale = scale if scale is not None else Hd**-0.5

    qf = q.reshape(B, T, KVH, G, Hd).astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("btkgd,bskd->bkgts", qf, kf)  # [B, KVH, G, T, S]

    if mask is not None:
        if mask.ndim == 2:
            m = mask[None, None, None, :, :]
        else:  # [B, T, S]
            m = mask[:, None, None, :, :]
        scores = jnp.where(m, scores, NEG_INF)

    if sinks is not None:
        sink = sinks.astype(jnp.float32).reshape(KVH, G)[None, :, :, None, None]
        sink = jnp.broadcast_to(sink, (B, KVH, G, T, 1))
        scores = jnp.concatenate([scores, sink], axis=-1)
        probs = jnp.exp(
            scores - jnp.max(scores, axis=-1, keepdims=True)
        )
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
        probs = probs[..., :-1]  # drop the sink column (no value)
    else:
        probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)

    # v's head dim may differ from q/k's (MLA caches qk_head for K but
    # v_head_dim for V)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v.astype(jnp.float32))
    return out.reshape(B, T, H, v.shape[-1]).astype(q.dtype)
