"""Flash attention for causal prefill (Pallas on TPU, jnp fallback).

The dense `ops.attention.attend` materializes the full [B, KVH, G, T, S]
f32 score tensor — at long-context prefill that is the dominant HBM
cost (a 8K x 8K f32 score block is 256 MB per head-group) and the reason
chunked prefill exists.  This kernel streams KV tiles through VMEM with
the online-softmax accumulator (m, l, acc) in scratch, so memory is
O(T x Hd) regardless of S, and the MXU sees [bq, Hd] x [Hd, bk] tiles.

Reference analog: the compression subsystem's Metal kernels show the
reference's pattern of hand-written GPU kernels for hot ops
(src/dnet/compression/kernels.py); attention is the TPU hot op worth the
same treatment.  Scope: CAUSAL SELF-ATTENTION against a slot-addressed
cache — query row i attends keys [0, pos + i] — covering llama-family,
deepseek-MLA (V's head dim may differ from Q/K's), and gpt_oss
full-attention prefill (per-head sink logits folded into the softmax
denominator at emit).  Sliding windows and sp sharding stay dense.

TPU grids run sequentially over the LAST axis, so the KV-tile axis comes
last and the scratch accumulator carries across its iterations; blocks
strictly above the causal diagonal are skipped (`pl.when`), halving the
work like every flash implementation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from dnet_tpu.utils.jax_compat import SDS_HAS_VMA, pcast_varying

NEG_INF = -1e30


def _flash_kernel(pos_ref, sink_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                  acc_ref, *, bq: int, bk: int, scale: float, n_s: int):
    """One (batch, head, q-tile, kv-tile) step of the online softmax.

    q_ref/k_ref [.., Hd]; v_ref/o_ref [.., Vd] (MLA: Vd may differ) —
    blocks of the NATIVE [B, T/S, heads, dim] layouts (no transposed copies
    of the cache); scratch m/l [bq, 1] f32, acc [bq, Vd] f32; pos SMEM [1];
    sink_ref SMEM [H] per-head sink logits (GPT-OSS: a virtual key that
    absorbs probability mass but contributes no value; NEG_INF = no sink,
    exp underflows to an exact no-op)."""
    import jax.experimental.pallas as pl

    h = pl.program_id(1)
    tq = pl.program_id(2)
    s = pl.program_id(3)
    pos = pos_ref[0]

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # this q-tile's LAST row attends keys <= pos + tq*bq + bq - 1; a kv
    # tile starting past that is fully masked for the whole tile -> skip
    q_hi = pos + (tq + 1) * bq - 1

    @pl.when(s * bk <= q_hi)
    def _fold():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale  # [bq, Hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [bk, Hd]
        # v may have a different head dim (MLA caches qk_head_dim keys but
        # v_head_dim values); acc is sized [bq, Vd]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        q_pos = pos + tq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = s * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        scores = jnp.where(k_pos <= q_pos, scores, NEG_INF)

        m_prev = m_ref[:]  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)  # [bq, bk]
        corr = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_ref[0, :, 0, :].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, Vd]
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new

    @pl.when(s == n_s - 1)
    def _emit():
        # fold the sink into the global softmax denominator exactly once
        # (same algebra as the dense op's virtual-key column)
        sink = sink_ref[h]
        m_fin = jnp.maximum(m_ref[:], sink)
        corr = jnp.exp(m_ref[:] - m_fin)
        l_fin = l_ref[:] * corr + jnp.exp(sink - m_fin)
        o_ref[0, :, 0, :] = (
            acc_ref[:] * corr / jnp.maximum(l_fin, 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("G", "scale", "bq", "bk", "interpret", "vma")
)
def _flash_pallas(q, k, v, pos, sinks, *, G: int, scale: float, bq: int,
                  bk: int, interpret: bool, vma: tuple = ()):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, Hd = q.shape
    S = k.shape[1]
    Vd = v.shape[-1]
    n_s = S // bk
    # inside shard_map the output is device-varying over the inputs' mesh
    # axes; check_vma requires the declaration (vma=() outside shard_map)
    kw = {"vma": frozenset(vma)} if (vma and SDS_HAS_VMA) else {}

    # grid (batch, head, q-tile, kv-tile); kv-tile LAST so the scratch
    # accumulator carries across its (sequential) iterations
    grid = (B, H, T // bq, n_s)
    kernel = functools.partial(
        _flash_kernel, bq=bq, bk=bk, scale=scale, n_s=n_s
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # pos [1]
            pl.BlockSpec(memory_space=pltpu.SMEM),  # sinks [H]
            pl.BlockSpec((1, bq, 1, Hd), lambda b, h, tq, s: (b, tq, h, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, 1, Hd), lambda b, h, tq, s: (b, s, h // G, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, 1, Vd), lambda b, h, tq, s: (b, s, h // G, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, 1, Vd), lambda b, h, tq, s: (b, tq, h, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, T, H, Vd), q.dtype, **kw),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, Vd), jnp.float32),
        ],
        interpret=interpret,
    )(pos, sinks, q, k, v)


def _flash_emulate(q, k, v, pos, sinks, *, scale: float, bk: int):
    """Plain-jnp twin of _flash_kernel: the same tile-by-tile online-softmax
    fold (f32, same operation order), for executed coverage where pallas
    cannot run — interpret mode inside shard_map discharges the kernel to a
    jaxpr whose constants stay vma-invariant (r4 diagnosis), so CPU mesh
    tests and dryruns run this emulation; real TPU runs the kernel.

    Folding every kv tile (no above-diagonal skip) is exact: tile 0 always
    holds an attendable key (slot 0 is causal for every row when pos >= 0),
    so m is finite after the first fold and a fully-masked later tile
    contributes exp(NEG_INF - m) == 0.0 to l/acc and leaves m unchanged —
    a bitwise no-op in f32."""
    from jax import lax

    B, T, H, Hd = q.shape
    S, KVH = k.shape[1], k.shape[2]
    G = H // KVH
    Vd = v.shape[-1]
    n_s = S // bk
    qf = q.reshape(B, T, KVH, G, Hd).astype(jnp.float32) * scale

    def fold(carry, s):
        m, l, acc = carry  # [B,KVH,G,T,1] x2, [B,KVH,G,T,Vd]
        k_t = lax.dynamic_slice_in_dim(k, s * bk, bk, 1).astype(jnp.float32)
        v_t = lax.dynamic_slice_in_dim(v, s * bk, bk, 1).astype(jnp.float32)
        scores = jnp.einsum("btkgd,bskd->bkgts", qf, k_t)  # [B,KVH,G,T,bk]
        q_pos = pos + jnp.arange(T)[:, None]
        k_pos = s * bk + jnp.arange(bk)[None, :]
        scores = jnp.where((k_pos <= q_pos)[None, None, None], scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bkgts,bskd->bkgtd", p, v_t)
        return (m_new, l, acc), None

    init = (
        jnp.full((B, KVH, G, T, 1), NEG_INF, jnp.float32),
        jnp.zeros((B, KVH, G, T, 1), jnp.float32),
        jnp.zeros((B, KVH, G, T, Vd), jnp.float32),
    )
    # the fold's outputs are varying over the inputs' mesh axes; the scan
    # carry must enter with the same vma (fresh zeros are invariant)
    axes = _vma_union(q, k, v, pos) or frozenset()
    if axes:
        init = tuple(
            pcast_varying(x, tuple(sorted(axes))) for x in init
        )
    (m, l, acc), _ = lax.scan(fold, init, jnp.arange(n_s))
    sink = sinks.astype(jnp.float32).reshape(KVH, G)[None, :, :, None, None]
    m_fin = jnp.maximum(m, sink)
    corr = jnp.exp(m - m_fin)
    l_fin = l * corr + jnp.exp(sink - m_fin)
    out = acc * corr / jnp.maximum(l_fin, 1e-30)  # [B,KVH,G,T,Vd]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, H, Vd).astype(q.dtype)


def _pick_tile(n: int, target: int) -> int:
    for t in (target, 128, 64, 32, 16, 8):
        if t <= n and n % t == 0:
            return t
    return 0


def _interpret() -> bool:
    from dnet_tpu.config import env_flag

    return env_flag("DNET_FLASH_INTERPRET")


_PROBE_WARNED = False


def _under_manual_mesh():
    """True when tracing inside shard_map (mesh ring / mesh-shard programs),
    False outside, None when the probe itself fails.

    Inside shard_map the kernels still run (r5): pallas_call outputs carry
    explicit vma declarations derived from the inputs' varying axes
    (`_vma_union`), and interpret mode — where pallas under shard_map is
    fundamentally broken (discharged-jaxpr constants stay vma-invariant) —
    runs the plain-jnp tile-fold emulation instead.  None makes callers
    fail CLOSED to the dense ops with ONE logged warning (the probe API is
    private-ish; a silent False after a jax upgrade would be an invisible
    perf cliff, a silent True a permanent kernel blackout)."""
    global _PROBE_WARNED
    try:
        return bool(jax.sharding.get_abstract_mesh().manual_axes)
    except AttributeError:
        # jax 0.4.x: no abstract-mesh API; inside shard_map the axis env
        # is non-empty (and empty under plain jit/eager), which is the
        # same True/False this probe needs
        try:
            from jax.core import nonempty_axis_env_DO_NOT_USE

            return bool(nonempty_axis_env_DO_NOT_USE())
        except Exception as exc:
            return _probe_failed(exc)
    except Exception as exc:
        return _probe_failed(exc)


def _probe_failed(exc) -> None:
    global _PROBE_WARNED
    if not _PROBE_WARNED:
        _PROBE_WARNED = True
        # lazy: this module must import without dragging in the logging
        # setup (kernel code is imported from bare jax scripts too)
        from dnet_tpu.utils.logger import get_logger

        get_logger().warning(
            "manual-mesh probe failed (%s: %s); flash kernels disabled "
            "— dense attention serves everywhere", type(exc).__name__, exc
        )
    return None


def _vma_union(*xs):
    """Union of the inputs' varying mesh axes (shard_map vma) — what a
    pallas_call's outputs must declare under check_vma.  On jax without
    the vma type system, falls back to ALL manual axes of the current
    trace (conservative but exact for shard_map bodies, where every value
    is per-device); None only if the probe API itself is unavailable
    (callers fall back to dense)."""
    if not hasattr(jax, "typeof"):
        from dnet_tpu.utils.jax_compat import manual_axis_names

        return manual_axis_names()
    out = frozenset()
    try:
        for x in xs:
            out |= frozenset(
                getattr(jax.typeof(jnp.asarray(x)), "vma", frozenset())
            )
    except Exception:
        return None
    return out


def flash_eligible(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> bool:
    """Kernel preconditions: GQA-divisible heads, tileable T/S, and a TPU
    backend (or the test override forcing interpret mode).  V's head dim
    may differ from Q/K's (MLA).  Inside shard_map the kernel runs with
    explicit output vma (or the jnp emulation under interpret); only a
    broken mesh/vma probe falls back to dense (warned once)."""
    if not _interpret() and jax.default_backend() != "tpu":
        return False
    um = _under_manual_mesh()
    if um is None or (um and _vma_union(q, k, v) is None):
        return False
    T, H = q.shape[1], q.shape[2]
    S, KVH = k.shape[1], k.shape[2]
    return (
        H % KVH == 0
        and T >= 8
        and _pick_tile(T, 128) > 0
        and _pick_tile(S, 128) > 0
    )


def flash_attend_causal(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    pos,
    scale: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Causal prefill attention: query row i attends cache slots [0, pos+i].

    q [B, T, H, Hd]; k [B, S, KVH, Hd], v [B, S, KVH, Vd] (the full cache;
    slots past pos+T are excluded by causality).  Equals
    `attend(q, k, v, mask=causal_mask(T, S, pos), sinks=sinks)` — the
    Pallas kernel runs on TPU (or under DNET_FLASH_INTERPRET=1 for CPU
    tests), the dense op otherwise.  sinks [H]: per-head attention-sink
    logits (GPT-OSS).
    """
    B, T, H, Hd = q.shape
    S, KVH = k.shape[1], k.shape[2]
    scale = Hd**-0.5 if scale is None else scale
    if T == 1:
        # decode: one query row against the (preallocated) cache — the
        # split-K sibling kernel streams only the LIVE tiles
        from dnet_tpu.ops.flash_decode import (
            flash_decode_attend,
            flash_decode_eligible,
        )

        if flash_decode_eligible(q, k):
            return flash_decode_attend(q, k, v, pos, scale=scale, sinks=sinks)
    if not flash_eligible(q, k, v):
        from dnet_tpu.ops.attention import attend, causal_mask

        return attend(q, k, v, mask=causal_mask(T, S, pos), scale=scale,
                      sinks=sinks)
    sink_arr = (
        jnp.full((H,), NEG_INF, dtype=jnp.float32)
        if sinks is None
        else sinks.astype(jnp.float32)
    )
    if _under_manual_mesh():
        if _interpret():
            # CPU mesh tests: pallas-in-shard_map interpret is broken, the
            # jnp emulation executes the identical fold
            return _flash_emulate(
                q, k, v, pos, sink_arr, scale=float(scale),
                bk=_pick_tile(S, 128),
            )
        vset = _vma_union(q, k, v, pos, sink_arr) or frozenset()
        return _flash_pallas(
            q, k, v, jnp.asarray([pos], dtype=jnp.int32), sink_arr,
            G=H // KVH, scale=float(scale),
            bq=_pick_tile(T, 128), bk=_pick_tile(S, 128),
            interpret=False, vma=tuple(sorted(vset)),
        )
    # native layouts throughout: BlockSpec index maps pick head h's KV row
    # h // G directly, so neither the query nor the (much larger) cache is
    # copied/transposed in HBM
    return _flash_pallas(
        q, k, v, jnp.asarray([pos], dtype=jnp.int32), sink_arr, G=H // KVH,
        scale=float(scale),
        bq=_pick_tile(T, 128), bk=_pick_tile(S, 128),
        interpret=_interpret(),
    )
