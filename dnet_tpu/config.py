"""Central configuration.

Layered precedence (low to high): built-in defaults -> `.env` file -> process
environment -> CLI overrides.  Mirrors the reference's ten `DNET_*`
pydantic-settings groups (reference: src/dnet/config.py:23-263) with a
dependency-free dataclass implementation (pydantic-settings is not available
in this image) plus TPU-specific groups (mesh/ICI).

Every field of every group is settable as ``<PREFIX><UPPER_NAME>`` in the
environment, e.g. ``DNET_GRPC_MAX_MESSAGE_MB=128``.

THIS MODULE IS THE ONLY SANCTIONED READER OF ``DNET_*`` ENVIRONMENT
VARIABLES (static-analysis check DL006, ``scripts/dnetlint.py``): a raw
``os.environ.get("DNET_...")`` elsewhere silently skips .env layering,
type casting, and ``.env.example`` generation.  Consumers use a
``Settings`` field; the handful of flags that must observe env flips
AFTER the settings cache warmed (test toggles, operator kill-switches)
go through :func:`env_flag` below.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional, Type, TypeVar

T = TypeVar("T", bound="_EnvGroup")

_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off", ""}


def _parse_bool(raw: str) -> bool:
    low = raw.strip().lower()
    if low in _TRUTHY:
        return True
    if low in _FALSY:
        return False
    raise ValueError(f"not a boolean: {raw!r}")


def _cast(raw: str, typ: Any) -> Any:
    # Optional[X] -> X for casting; "none"/"" selects None.
    import typing

    origin = typing.get_origin(typ)
    if origin is typing.Union:
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if raw.strip().lower() in {"none", "null", ""}:
            return None
        typ = args[0]
    if typ is bool:
        return _parse_bool(raw)
    if typ is int:
        return int(raw)
    if typ is float:
        return float(raw)
    if typ is Path:
        return Path(raw).expanduser()
    if typ is str:
        return raw
    if typing.get_origin(typ) is list or typ is list:
        return [s.strip() for s in raw.split(",") if s.strip()]
    return raw


@functools.lru_cache(maxsize=8)
def _load_dotenv_cached(path: str, mtime: float) -> dict[str, str]:
    result: dict[str, str] = {}
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        key, _, value = line.partition("=")
        result[key.strip()] = value.strip().strip("'\"")
    return result


def load_dotenv(path: str | Path = ".env") -> dict[str, str]:
    """Parse a KEY=VALUE .env file (comments and blank lines ignored).

    Cached by (path, mtime) so the ten settings groups constructed by
    ``Settings()`` share one read.
    """
    p = Path(path)
    try:
        mtime = p.stat().st_mtime
    except OSError:
        return {}
    return _load_dotenv_cached(str(p), mtime)


class _EnvGroup:
    """Mixin: populate dataclass fields from `<env_prefix><FIELD>` vars."""

    env_prefix: str = "DNET_"

    @classmethod
    def from_env(cls: Type[T], env: Optional[dict[str, str]] = None) -> T:
        source: dict[str, str] = {}
        source.update(load_dotenv(os.environ.get("DNET_ENV_FILE", ".env")))
        source.update(os.environ)
        if env:
            source.update(env)
        kwargs: dict[str, Any] = {}
        for f in dataclasses.fields(cls):  # type: ignore[arg-type]
            key = f"{cls.env_prefix}{f.name.upper()}"
            if key in source:
                try:
                    kwargs[f.name] = _cast(source[key], cls.type_hint(f))  # type: ignore[attr-defined]
                except (ValueError, TypeError) as exc:
                    raise ValueError(f"bad value for {key}: {exc}") from exc
        return cls(**kwargs)  # type: ignore[call-arg]


# dataclasses stores string annotations under `from __future__ import
# annotations`; resolve them once per class.
def _resolve_hints(cls: type) -> None:
    import typing

    hints = typing.get_type_hints(cls)

    def type_hint(f: dataclasses.Field) -> Any:
        return hints[f.name]

    cls.type_hint = staticmethod(type_hint)  # type: ignore[attr-defined]


def _default_log_dir() -> Path:
    try:
        return Path("~/.dnet-tpu/logs").expanduser()
    except RuntimeError:  # no resolvable home dir (bare container uid)
        return Path("/tmp/dnet-tpu-logs")


@dataclass
class LogSettings(_EnvGroup):
    env_prefix = "DNET_LOG_"
    level: str = "INFO"
    dir: Path = field(default_factory=_default_log_dir)
    to_file: bool = True


@dataclass
class ObsSettings(_EnvGroup):
    """Observability: [PROFILE] log gating and device-sync knobs.

    Reference: src/dnet/core/observability.py:31-83.
    """

    env_prefix = "DNET_OBS_"
    enabled: bool = False
    sync_per_layer: bool = False
    sync_every_n: int = 0
    # SLO targets over a rolling window (obs/slo.py): 0 disables a target.
    # Burning SLOs flip /health to "degraded" and export dnet_slo_* gauges.
    slo_window_s: float = 300.0
    slo_ttft_p95_ms: float = 0.0
    slo_decode_p95_ms: float = 0.0
    slo_availability: float = 0.0  # e.g. 0.999; fraction of requests OK
    # /v1/cluster/metrics + cluster timeline: per-shard HTTP fetch timeout
    cluster_scrape_timeout_s: float = 5.0
    # flight-recorder sampling under load: record every Nth request's full
    # span timeline (summary spans — ttft, the closing request span — are
    # recorded for EVERY request regardless).  1 = record everything; N > 1
    # keeps a load run from thrashing the bounded timeline ring.
    trace_sample: int = 1
    # Perfetto trace export (obs/trace.py, GET /v1/debug/trace):
    # serving-window dump default horizon and a hard cap on emitted trace
    # events (oldest timelines dropped first past the cap)
    trace_window_s: float = 120.0
    trace_max_events: int = 50000
    # scheduler tick flight-recorder ring capacity (sched/flight.py,
    # GET /v1/debug/sched); 0 disables capture entirely
    tick_records: int = 256
    # structured wide-event journal (obs/events.py, GET /v1/debug/events):
    # bounded in-memory ring capacity (oldest evicted past it, counted as
    # dropped) and an optional JSONL file sink ("" disables the file)
    events_records: int = 2048
    events_path: str = ""

    def sync_stride(self) -> int:
        """Normalized decode-step sync cadence: 0 = never fence, N >= 1 =
        fence every N steps (1 = every step).  THE place owning the 0-vs-1
        semantics — call sites must use this, not the raw field (negative
        values clamp to never)."""
        return max(int(self.sync_every_n), 0)


@dataclass
class KVSettings(_EnvGroup):
    """KV-cache defaults (bits=0 means unquantized bf16)."""

    env_prefix = "DNET_KV_"
    bits: int = 0
    group_size: int = 64
    max_seq_len: int = 4096
    ttl_seconds: float = 600.0
    # paged KV (dnet_tpu/kv/): block-granular allocation with per-sequence
    # page tables, refcounted copy-on-write prefix sharing, and free-block
    # admission instead of slots x max_seq dense pinning.  Local/Batched
    # engines; the dense path stays the default.
    paged: bool = False
    # tokens per KV block (the allocation granule); must divide max_seq
    block_tokens: int = 16
    # total pool capacity in blocks; 0 = auto-size to the engine's dense
    # equivalent (slots x max_seq / block_tokens)
    pool_blocks: int = 0
    # ragged paged attention (ops/paged_attention.py): decode attends the
    # block pool IN PLACE through per-sequence page tables instead of the
    # gather->step->scatter sandwich.  Requires paged KV; engines fall back
    # to dense-gather for layouts the kernel refuses (quantized caches,
    # non-llama-family attention stacks).
    ragged: bool = False


@dataclass
class ComputeSettings(_EnvGroup):
    env_prefix = "DNET_COMPUTE_"
    wire_dtype: str = "bfloat16"  # activations on the wire (bf16 is TPU-native)
    compute_dtype: str = "bfloat16"
    window_size: int = 0  # 0 = all assigned layers in one window
    residency_windows: int = 2
    donate_activations: bool = True
    # MoE compute path: dense | auto | dispatch | a2a (ops/moe.py).  dense
    # is exact (reference semantics) and the default; auto picks dense for
    # decode-size token counts, capacity dispatch for prefill, and
    # all_to_all expert parallelism when a tp axis is present — capacity
    # dispatch may DROP over-capacity tokens (GShard semantics), a
    # throughput trade the operator opts into.
    moe_impl: str = "dense"
    # per-expert capacity = ceil(k * n_tokens * factor / n_experts);
    # <= 0 selects the exact no-drop capacity (C = n_tokens)
    moe_capacity_factor: float = 1.25


@dataclass
class TransportSettings(_EnvGroup):
    env_prefix = "DNET_TRANSPORT_"
    compress: bool = False
    compress_pct: float = 0.5
    compress_quant_bits: int = 0
    send_retries: int = 3
    stream_idle_sweep_s: float = 30.0
    stream_backoff_s: float = 0.25


@dataclass
class WireSettings(_EnvGroup):
    """Overlapped quantized wire pipeline (transport/wire_pipeline.py).

    ``DNET_WIRE_PIPELINE=1`` takes the hop codec off the serial send path:
    the shard compute thread only LAUNCHES the on-device encode (jitted
    quant/sparsify with a donated activation buffer) and hands the pending
    device buffers to the transport tx stage, which finishes the D2H
    readback + byte packing off-thread while the next frame computes; the
    receive side symmetrically launches H2D upload + on-device dequant at
    ingress so the dequant of frame N+1 overlaps frame N's compute.  A
    bounded ``DEPTH``-slot ring of encode buffers provides backpressure.
    ``CODEC`` picks the hop codec: ``auto`` (the default — the ring
    manager resolves per hop: lossy ``qsparse8`` for hops that CROSS
    hosts, ``lossless`` for same-host/loopback hops and single-shard
    rings, so greedy SSE streams stay byte-identical wherever no DCN is
    paid), ``lossless`` (wire-dtype cast, exact, everywhere), or
    ``qsparse8`` (int8-affine kept columns, ~4x fewer bytes, lossy,
    everywhere).
    The gate is also honored as a raw env flip via
    ``env_flag("DNET_WIRE_PIPELINE")`` so post-cache toggles (tests,
    operators) still see it.
    """

    env_prefix = "DNET_WIRE_"
    # master switch: double-buffered encode/decode overlap on shard hops
    pipeline: bool = False
    # hop codec default: auto | lossless | qsparse8 (auto = inter-host
    # hops ride qsparse8, same-host/loopback hops stay lossless)
    codec: str = "auto"
    # column drop fraction the qsparse8 hop codec uses when transport
    # compression is not separately configured
    qsparse_pct: float = 0.5
    # int8 quant group along kept columns; frames with fewer kept columns
    # than one group fall back to per-tensor fp32 scales (gs=0 tag)
    group_size: int = 64
    # encode-buffer ring depth: how many launched-but-unsent frames the
    # compute thread may run ahead of the tx readback (backpressure bound)
    depth: int = 2


@dataclass
class ResilienceSettings(_EnvGroup):
    """Request survival: retry/backoff policy + transparent decode resume.

    `resume=1` turns a mid-decode shard failure from a surfaced 503 into a
    checkpoint -> wait-for-recovery -> replay-prefill cycle on the SAME
    client stream (dnet_tpu/resilience/checkpoint.py).  The retry knobs
    scale the default unary-RPC backoff policy (resilience/policy.py);
    per-RPC-class overrides stay in code.
    """

    env_prefix = "DNET_RESILIENCE_"
    # transparent decode resume across shard failure (InferenceManager)
    resume: bool = False
    # per-resume budget for the ring to become healthy again before the
    # original error is surfaced to the client
    resume_deadline_s: float = 30.0
    # resume attempts per request; past this the failure surfaces
    max_resumes: int = 2
    # default unary-RPC retry policy (exponential backoff + full jitter)
    retry_attempts: int = 3
    retry_base_s: float = 0.05
    retry_max_s: float = 2.0
    # 0 = nondeterministic jitter; nonzero seeds the jitter RNG (tests)
    retry_jitter_seed: int = 0


@dataclass
class AdmissionSettings(_EnvGroup):
    """Overload survival (dnet_tpu/admission/): bounded admission, load
    shedding, end-to-end deadlines, graceful drain.

    The wait queue holds at most ``ADMIT_QUEUE_DEPTH`` requests beyond the
    executing set (``DNET_API_MAX_CONCURRENT_REQUESTS``); the rest shed
    immediately with 429 + ``Retry-After`` derived from the observed
    service rate.  ``REQUEST_DEADLINE_S`` (per-request ``deadline_s``
    overrides it) rides activation frame headers so shards drop expired
    frames at dequeue.  On SIGTERM the server drains: 503 for new work,
    in-flight requests bounded by ``DRAIN_DEADLINE_S``.
    """

    env_prefix = "DNET_"
    # waiting requests beyond the executing set; 0 = shed everything that
    # cannot start immediately
    admit_queue_depth: int = 32
    # longest a request may wait for a slot before shedding with 429
    admit_queue_timeout_s: float = 10.0
    # default end-to-end deadline; 0 disables (per-request `deadline_s`
    # still applies when set)
    request_deadline_s: float = 0.0
    # how long SIGTERM waits for in-flight requests before tearing down
    drain_deadline_s: float = 30.0


@dataclass
class LoadgenSettings(_EnvGroup):
    """Serving-grade load generation (dnet_tpu/loadgen/): an OPEN-LOOP
    arrival process (requests fire on schedule, never gated on completions)
    of N concurrent OpenAI-API streaming clients with a seeded mixed
    prompt/output-length workload.  `bench_serve.py` drives it and emits a
    machine-readable ``BENCH_SERVE_*.json`` report (goodput over completed
    requests only, TTFT/TPOT/E2E tail percentiles, shed-rate breakdown,
    SLO cross-validation, decode-phase and JIT-compile summaries).
    """

    env_prefix = "DNET_LOADGEN_"
    # workload schedule: a pure function of (seed, requests, rate, buckets)
    seed: int = 0
    requests: int = 64
    # mean arrival rate; poisson draws exponential inter-arrivals, fixed
    # spaces arrivals exactly 1/rate apart
    rate_rps: float = 8.0
    arrival: str = "poisson"  # poisson | fixed
    # mixed length classes "prompt:max_tokens,..." (tokens are exact for
    # byte-level tokenizers, approximate for BPE)
    buckets: str = "8:16,32:8,64:4"
    # optional comma floats weighting the buckets (default: uniform)
    weights: str = ""
    temperature: float = 0.0
    # report measurement starts here: requests SCHEDULED before warmup_s
    # still run (they warm compiles/caches) but are excluded from goodput
    # and percentiles
    warmup_s: float = 0.0
    # per-request client-side budget (stream must finish within this)
    timeout_s: float = 120.0


@dataclass
class MembershipSettings(_EnvGroup):
    """Elastic ring membership (dnet_tpu/membership/): topology epochs,
    quarantine, and automatic shard rejoin.

    With auto-recovery on, a permanently lost shard is fenced out by an
    epoch-bumping re-solve and moves to a QUARANTINE list that keeps
    health-probing it.  ``DNET_REJOIN=1`` lets a quarantined shard that
    probes green for ``REJOIN_STABLE_S`` seconds trigger a re-profile +
    re-solve through the delta-reload path, restoring full capacity with
    no operator action.  ``RECOVERY_MAX_ROUNDS`` bounds the convergence
    loop when further shards die during an in-flight recovery.
    """

    env_prefix = "DNET_"
    # automatic rejoin of quarantined shards that probe healthy again
    rejoin: bool = False
    # consecutive-green seconds before a quarantined shard may rejoin
    rejoin_stable_s: float = 15.0
    # recovery convergence: max re-solve rounds per failure burst (each
    # round re-checks down_shards() after its reload)
    recovery_max_rounds: int = 3


@dataclass
class SchedSettings(_EnvGroup):
    """Iteration-level continuous-batching scheduler (dnet_tpu/sched/).

    ``DNET_SCHED=1`` makes the scheduler the serving engine for local
    model loads: every tick packs up to ``SCHED_TOKEN_BUDGET`` tokens of
    chunked-prefill segments plus one decode step per running sequence
    into one batch plan, admits new work only when the paged-KV block
    pool can cover it, and preempts the lowest-priority sequence back to
    WAITING (paged prefix kept) under block starvation.  Off (the
    default), the legacy engine-selection paths serve unchanged.  The
    gate is also honored as a raw env flip via ``env_flag("DNET_SCHED")``
    so post-cache toggles (tests, operators) still see it.
    """

    env_prefix = "DNET_"
    # master switch: the scheduler becomes the local serving engine
    sched: bool = False
    # per-tick token budget shared by chunked-prefill segments (1 token
    # each) and decode steps (1 per running sequence)
    sched_token_budget: int = 2048
    # largest chunked-prefill segment per request per tick
    sched_prefill_chunk: int = 256
    # batch lanes the scheduler engine allocates; 0 = max(batch_slots, 8)
    sched_slots: int = 0


@dataclass
class FleetSettings(_EnvGroup):
    """Fleet routing (dnet_tpu/fleet/): N ring replicas behind one
    prefix-affine, least-loaded front door.

    ``DNET_FLEET=N`` (N > 1) puts the FleetManager in front of
    /v1/chat/completions: requests route prefix-affinity-first (sticking
    a conversation to the replica holding its COW prefix blocks), then
    least-loaded by live admission occupancy; a replica that dies
    mid-stream fails over to a survivor via deterministic replay.  The
    default 1 keeps today's single-ring serve path byte-identical — the
    fleet layer is never constructed.
    """

    env_prefix = "DNET_"
    # replica count the front door expects; 1 = no fleet layer at all
    fleet: int = 1
    # bounded LRU affinity table: conversations tracked before the
    # coldest sticky entry is evicted
    fleet_affinity_capacity: int = 512
    # leading prefix units (text chars) hashed into the affinity key
    fleet_affinity_prefix: int = 256
    # migrate in-flight streams off a dead replica via replay; off =
    # a mid-stream death surfaces as an in-band stream error instead
    fleet_failover: bool = True
    # emulated device-bound decode: minimum wall-clock ms per batched
    # decode step.  On a real TPU ring the host mostly WAITS on the
    # device, so replicas scale across hosts; a CPU-only container has
    # no such idle time and N in-process replicas just contend for the
    # same cores.  A nonzero pace restores the device-bound regime for
    # fleet scaling benches (every token still crosses the full
    # engine/KV/admission/SSE path).  0 = off, no behavior change.
    fleet_decode_pace_ms: float = 0.0


@dataclass
class SanSettings(_EnvGroup):
    """Runtime concurrency sanitizer (dnet_tpu/analysis/runtime/, "dsan").

    ``DNET_SAN=1`` arms the suite: the event-loop stall watchdog,
    ownership-domain guards on the declared shared structures,
    lock-acquisition-order tracking, and the task-leak audit.  Findings
    (DS001-DS006) reuse the dnetlint Finding model and merge into the
    ``ANALYSIS_r<NN>.json`` records.  Off (the default), every hook is a
    no-op — nothing is wrapped, zero cost on the serving path.  The gate
    is read via ``config.env_flag`` so post-cache env flips (the pytest
    fixtures) still arm it.
    """

    env_prefix = "DNET_"
    # master switch; also honored as a raw env flip via env_flag("DNET_SAN")
    san: bool = False
    # loop blocked longer than this is a DS001 stall finding
    san_stall_ms: float = 250.0
    # watchdog sampling cadence; 0 = stall_ms / 4
    san_poll_ms: float = 0.0
    # where sanitized runs persist findings for the dnetlint merge;
    # "" = <repo>/.dsan-findings.json
    san_report: str = ""


@dataclass
class ChaosSettings(_EnvGroup):
    """Deterministic fault injection (dnet_tpu/resilience/chaos.py).

    ``DNET_CHAOS="shard_compute:error_at:5,send_activation:error:0.1,
    token_cb:delay:50ms"`` — comma-separated ``point:kind:param`` specs over
    the named injection points; the schedule is a pure function of
    ``DNET_CHAOS_SEED`` and the per-point call counters, so a failing run
    replays exactly.
    """

    env_prefix = "DNET_"
    chaos: str = ""
    chaos_seed: int = 0


@dataclass
class TpSettings(_EnvGroup):
    """Intra-shard tensor parallelism (parallel/tp.py, parallel/
    tp_collectives.py).

    ``DNET_TP=N`` makes a ring shard run its layer window tensor-parallel
    over N host-local chips on a ("batch", "model") NamedSharding mesh:
    weights load pre-sharded (per-chip slices, never a full tensor on one
    chip), the KV cache shards on the head axis, and each layer pays two
    collectives — attention out-proj and MLP down-proj all-reduces —
    routed through the quantizable seam.  ``TP_COLLECTIVE`` picks their
    wire format: ``lossless`` (exact psum — greedy SSE byte-identical to
    tp=1), ``q8`` (EQuARX-style grouped-int8: 1-byte codes + per-group
    scale/bias instead of 2-4 byte floats), or ``auto`` (q8 on real
    accelerator meshes, lossless on CPU).  A solver-placed topology
    overrides the env default per shard via the load body's
    ``tp_degree``.  1 = off, today's single-chip behavior.
    """

    env_prefix = "DNET_"
    # tensor-parallel degree for shards loaded without an explicit
    # tp_degree (1 = off); must divide the model's attention/KV head counts
    tp: int = 1
    # collective wire format: auto | lossless | q8
    tp_collective: str = "auto"
    # int8 quant group along the flattened activation for q8 collectives
    tp_group_size: int = 64


@dataclass
class GrpcSettings(_EnvGroup):
    """gRPC channel tuning (reference: src/dnet/utils/grpc_config.py:29-53)."""

    env_prefix = "DNET_GRPC_"
    max_message_mb: int = 64
    max_concurrent_streams: int = 1024
    keepalive_time_ms: int = 20000
    keepalive_timeout_ms: int = 10000
    http2_bdp_probe: bool = False


@dataclass
class ApiSettings(_EnvGroup):
    env_prefix = "DNET_API_"
    host: str = "0.0.0.0"
    http_port: int = 8080
    grpc_port: int = 58080
    callback_addr: str = ""  # override for non-loopback token callback
    request_timeout_s: float = 300.0
    max_concurrent_requests: int = 8
    max_batch_size: int = 8
    models_dir: str = "~/.dnet-tpu/models"
    max_seq_len: int = 4096
    param_dtype: str = "bfloat16"
    health_interval_s: float = 5.0
    health_fail_threshold: int = 3
    # 0 = serve weights in param_dtype; 8 = int8, 4 = packed-int4 weight-only
    # quantization (per-group symmetric, ops/quant.py) — ~2x / ~4x decode
    # roofline on HBM-bound batch-1 serving
    weight_quant_bits: int = 0
    # quantization group size along the contraction dim (0 = quantizer
    # default: 128 for int8, 64 for int4).  Tensor-parallel serving needs a
    # value dividing in/tp for every quantized weight.
    weight_quant_group: int = 0
    # >1 = continuous batching: that many KV slots share one vmapped decode
    # program (core/batch.py); concurrent requests coalesce per step
    batch_slots: int = 1
    # >0 = cache that many full-prompt KV snapshots; a request whose prompt
    # EXTENDS a cached prompt (multi-turn chat resending its history)
    # prefills only the new suffix (core/prefix_cache.py).  Exact-prefix
    # match; each snapshot is a full KV alloc.  Local/batched engines only.
    prefix_cache: int = 0
    # >0 = prompt-lookup speculative decoding: draft that many tokens per
    # verify forward (core/spec.py).  Greedy-exact; eligible requests emit
    # 1..L+1 tokens per weight read.  Local and mesh engines (batch 1).
    spec_lookahead: int = 0
    # draft-MODEL speculation (single-process serving, LocalEngine only):
    # a smaller same-vocab checkpoint drafts SPEC_LOOKAHEAD tokens per
    # verify block instead of prompt-lookup — better acceptance on
    # non-repetitive text.  Checkpoint path or models_dir id; "" = off.
    draft_model: str = ""
    # ring decode grants: a token frame may authorize the TAIL shard to
    # feed up to this many sampled tokens straight back into the ring
    # (tail -> head hop), removing the per-token API round trip.  The tail
    # halts on EOS / cache capacity; overshoot past a stop SEQUENCE is
    # discarded like local decode chunks.  0 disables.
    ring_auto_steps: int = 16
    # compile the decode-chunk program matrix at LOAD time (no first-request
    # ramp stall).  0 defers every compile to first use — faster model
    # hot-swaps where startup latency matters more than first-token latency
    # (CI model-matrix loops, A/B harnesses).
    warm_on_load: bool = True
    # batched lanes over the ring: >1 coalesces that many concurrent
    # requests' decode steps into ONE multi-lane ring pass (shard/lanes.py).
    # Needs a single-round resident-weight topology; composes with
    # mesh-backed shards.  Grants and ring speculation are per-nonce
    # self-pacing and turn off when lanes are on.  0/1 = off.
    ring_lanes: int = 0


@dataclass
class ShardSettings(_EnvGroup):
    env_prefix = "DNET_SHARD_"
    host: str = "0.0.0.0"
    http_port: int = 8081
    grpc_port: int = 58081
    queue_size: int = 256
    name: str = ""
    models_dir: str = "~/.dnet-tpu/models"
    # per-layer repack cache for weight streaming (reference repack.py)
    repack_dir: str = "~/.dnet-tpu/repacked"
    # host-local mesh under this shard's ring node: the layer window runs
    # tensor-parallel (tp) / sequence-parallel (sp) across the host's ICI
    # chips while ring hops stay gRPC/DCN (parallel/shard_mesh.py).
    # tp=1/sp=1 = single-device; tp=-1 = every local device on the tp axis.
    # A /load_model request with explicit mesh fields overrides these.
    mesh_tp: int = 1
    mesh_sp: int = 1


@dataclass
class TopologySettings(_EnvGroup):
    env_prefix = "DNET_TOPOLOGY_"
    solver: str = "auto"  # auto | greedy | milp
    mip_gap: float = 1e-4
    seq_len: int = 4096


@dataclass
class MeshSettings(_EnvGroup):
    """TPU mesh axes used by the in-slice single-program ring / TP / SP."""

    env_prefix = "DNET_MESH_"
    pp: int = 0  # 0 = infer from device count
    tp: int = 1
    dp: int = 1
    sp: int = 1
    backend: str = ""  # "" = jax default
    # multi-host pods: when set, jax.distributed.initialize() runs before
    # the first backend use so jax.devices() spans every host of the slice
    # and the mesh engines build over the GLOBAL device set (DCN-connected
    # slices included) — the TPU analog of the reference's NCCL/MPI-style
    # multi-node backend.  Format "host:port" of process 0.
    coordinator: str = ""
    num_processes: int = 0  # 0 = single-process (no distributed init)
    process_id: int = 0


@dataclass
class Settings:
    log: LogSettings = field(default_factory=LogSettings.from_env)
    obs: ObsSettings = field(default_factory=ObsSettings.from_env)
    kv: KVSettings = field(default_factory=KVSettings.from_env)
    compute: ComputeSettings = field(default_factory=ComputeSettings.from_env)
    transport: TransportSettings = field(default_factory=TransportSettings.from_env)
    wire: WireSettings = field(default_factory=WireSettings.from_env)
    resilience: ResilienceSettings = field(default_factory=ResilienceSettings.from_env)
    admission: AdmissionSettings = field(default_factory=AdmissionSettings.from_env)
    loadgen: LoadgenSettings = field(default_factory=LoadgenSettings.from_env)
    membership: MembershipSettings = field(default_factory=MembershipSettings.from_env)
    sched: SchedSettings = field(default_factory=SchedSettings.from_env)
    fleet: FleetSettings = field(default_factory=FleetSettings.from_env)
    san: SanSettings = field(default_factory=SanSettings.from_env)
    tp: TpSettings = field(default_factory=TpSettings.from_env)
    chaos: ChaosSettings = field(default_factory=ChaosSettings.from_env)
    grpc: GrpcSettings = field(default_factory=GrpcSettings.from_env)
    api: ApiSettings = field(default_factory=ApiSettings.from_env)
    shard: ShardSettings = field(default_factory=ShardSettings.from_env)
    topology: TopologySettings = field(default_factory=TopologySettings.from_env)
    mesh: MeshSettings = field(default_factory=MeshSettings.from_env)


for _cls in (
    LogSettings,
    ObsSettings,
    KVSettings,
    ComputeSettings,
    TransportSettings,
    WireSettings,
    ResilienceSettings,
    AdmissionSettings,
    LoadgenSettings,
    MembershipSettings,
    SchedSettings,
    FleetSettings,
    SanSettings,
    TpSettings,
    ChaosSettings,
    GrpcSettings,
    ApiSettings,
    ShardSettings,
    TopologySettings,
    MeshSettings,
):
    _resolve_hints(_cls)


def env_flag(name: str, default: bool = False) -> bool:
    """Sanctioned RAW process-env boolean read — the documented DL006
    escape hatch for flags that must see ``os.environ`` flips after the
    ``get_settings()`` cache warmed: the ``DNET_KV_PAGED`` /
    ``DNET_PROFILE`` test toggles and the ``DNET_FLASH_DECODE`` /
    ``DNET_FLASH_INTERPRET`` operator kill-switches.  Unset,
    set-but-empty (``DNET_X=``, the shell/compose idiom for "unset"),
    or unparseable values return ``default`` — an empty string must not
    silently disable a default-enabled kill-switch.  Everything else
    goes through a ``Settings`` field."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return _parse_bool(raw)
    except ValueError:
        return default


@functools.lru_cache(maxsize=1)
def get_settings() -> Settings:
    return Settings()


def reset_settings_cache() -> None:
    """For tests that mutate the environment."""
    get_settings.cache_clear()
