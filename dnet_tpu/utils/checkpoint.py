"""Safetensors checkpoint access: lazy, per-layer, mmap-backed.

The TPU analog of the reference's model metadata subsystem
(src/dnet/utils/model.py:388-467): parse safetensors headers without loading
data, classify tensors into per-layer / embed / final-norm / lm-head groups,
and load only what a shard's assignment needs.  `safetensors.safe_open`
gives zero-copy mmap reads, so "load layer i" touches only that layer's
byte-ranges — the role madvise/MappedFile plays in the reference
(src/dnet/utils/layer_manager.py:107-215).
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np
from safetensors import safe_open

from dnet_tpu.utils.logger import get_logger

log = get_logger()

_LAYER_RE = re.compile(r"^model\.layers\.(\d+)\.(.+)$")


class Checkpoint:
    """An HF-format model directory: config.json + *.safetensors [+ index]."""

    def __init__(self, model_dir: str | Path, use_native: bool = True):
        self.dir = Path(model_dir)
        cfg_path = self.dir / "config.json"
        if not cfg_path.is_file():
            raise FileNotFoundError(f"no config.json in {self.dir}")
        self.config: dict = json.loads(cfg_path.read_text())

        # tensor name -> file path
        self.tensor_file: Dict[str, Path] = {}
        index = self.dir / "model.safetensors.index.json"
        if index.is_file():
            weight_map = json.loads(index.read_text())["weight_map"]
            for name, fname in weight_map.items():
                self.tensor_file[name] = self.dir / fname
        else:
            files = sorted(self.dir.glob("*.safetensors"))
            if not files:
                raise FileNotFoundError(f"no .safetensors in {self.dir}")
            for f in files:
                with safe_open(f, framework="numpy") as st:
                    for name in st.keys():
                        self.tensor_file[name] = f

        # classify
        self.layer_tensors: Dict[int, Dict[str, str]] = {}  # layer -> suffix -> full name
        self.edge_tensors: Dict[str, str] = {}
        for name in self.tensor_file:
            m = _LAYER_RE.match(name)
            if m:
                self.layer_tensors.setdefault(int(m.group(1)), {})[m.group(2)] = name
            else:
                self.edge_tensors[name] = name

        self._handles: Dict[Path, object] = {}
        # native mmap fastpath (zero-copy views + madvise streaming); any
        # failure degrades to the python safetensors reader per-file
        self._native: Dict[Path, Optional[object]] = {}
        self._use_native = use_native

    def _native_handle(self, path: Path):
        if not self._use_native:
            return None
        if path not in self._native:
            try:
                from dnet_tpu.utils.native_store import NativeSafetensors, available

                self._native[path] = NativeSafetensors(path) if available() else None
            except Exception as exc:  # corrupt file / platform quirk
                log.warning("native mmap failed for %s (%s); python IO", path, exc)
                self._native[path] = None
        return self._native[path]

    # ---- metadata -----------------------------------------------------
    @property
    def num_layers(self) -> int:
        return int(self.config["num_hidden_layers"])

    def _handle(self, path: Path):
        h = self._handles.get(path)
        if h is None:
            h = safe_open(path, framework="numpy")
            self._handles[path] = h
        return h

    def tensor_meta(self, name: str) -> tuple[list[int], str]:
        sl = self._handle(self.tensor_file[name]).get_slice(name)
        return list(sl.get_shape()), str(sl.get_dtype())

    def layer_nbytes(self, layer: int) -> int:
        """Byte size of one layer's tensors, from headers only (solver input)."""
        total = 0
        for full in self.layer_tensors.get(layer, {}).values():
            shape, dtype = self.tensor_meta(full)
            itemsize = _dtype_size(dtype)
            n = 1
            for s in shape:
                n *= s
            total += n * itemsize
        return total

    # ---- loading ------------------------------------------------------
    def load_tensor(self, name: str) -> np.ndarray:
        st = self._native_handle(self.tensor_file[name])
        if st is not None and name in st.tensors:
            return st.tensor(name)  # zero-copy mmap view
        return self._handle(self.tensor_file[name]).get_tensor(name)

    def load_layer_raw(self, layer: int) -> Dict[str, np.ndarray]:
        """One layer's tensors keyed by suffix (prefix stripped)."""
        if layer not in self.layer_tensors:
            raise KeyError(f"layer {layer} not in checkpoint")
        return {
            suffix: self.load_tensor(full)
            for suffix, full in self.layer_tensors[layer].items()
        }

    def load_edge_raw(self, names: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
        """Non-layer tensors (embed/final-norm/lm-head), all or a subset."""
        keys = names if names is not None else list(self.edge_tensors)
        return {k: self.load_tensor(k) for k in keys if k in self.edge_tensors}

    # ---- page-cache streaming (native layer_manager analog) -----------
    def _layer_names_by_file(self, layer: int) -> Dict[Path, List[str]]:
        by_file: Dict[Path, List[str]] = {}
        for full in self.layer_tensors.get(layer, {}).values():
            by_file.setdefault(self.tensor_file[full], []).append(full)
        return by_file

    def prefetch_layer(self, layer: int, sync: bool = False) -> None:
        """madvise(WILLNEED) + background page-touch of one layer's spans,
        so its disk reads overlap compute (reference layer_manager.py:107-215
        prefetch modes).  No-op when the native store is unavailable."""
        for path, names in self._layer_names_by_file(layer).items():
            st = self._native_handle(path)
            if st is not None:
                st.prefetch(names, sync=sync)

    def release_layer(self, layer: int) -> None:
        """madvise(DONTNEED) an evicted layer's page-cache spans
        (reference layer_manager.py:217-227)."""
        for path, names in self._layer_names_by_file(layer).items():
            st = self._native_handle(path)
            if st is not None:
                st.release(names)

    def close(self) -> None:
        self._handles.clear()
        for st in self._native.values():
            if st is not None:
                st.close()
        self._native.clear()


_SAFETENSOR_SIZES = {
    "F64": 8, "F32": 4, "F16": 2, "BF16": 2,
    "I64": 8, "I32": 4, "I16": 2, "I8": 1, "U8": 1, "BOOL": 1,
    "F8_E4M3": 1, "F8_E5M2": 1, "U32": 4, "U16": 2, "U64": 8,
}


def _dtype_size(dtype: str) -> int:
    key = dtype.upper().removeprefix("DTYPE.")
    if key in _SAFETENSOR_SIZES:
        return _SAFETENSOR_SIZES[key]
    return np.dtype(dtype.lower()).itemsize


def save_checkpoint(
    model_dir: str | Path, config: dict, tensors: Dict[str, np.ndarray]
) -> None:
    """Write an HF-style single-file checkpoint (tests + repack use this)."""
    from safetensors.numpy import save_file

    d = Path(model_dir)
    d.mkdir(parents=True, exist_ok=True)
    (d / "config.json").write_text(json.dumps(config, indent=2))
    save_file(tensors, d / "model.safetensors")
