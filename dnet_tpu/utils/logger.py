"""Central logger with [PROFILE] gating and per-process file logs.

Reference behavior: src/dnet/utils/logger.py:53-112 — a single "dnet" logger,
env-driven level, `[PROFILE]`-tagged lines filtered out unless profiling is
enabled, and per-process file handlers (api vs shard-PID names).

Two contracts this module owns:

- **Foreign handlers survive reconfiguration.**  setup_logger only removes
  handlers it installed (tagged `_dnet_owned`) — the TUI's live-feed
  handler (tui.py) and any test-attached capture handler stay wired when a
  CLI later calls `setup_logger(role=...)`.
- **Request context on every line.**  The `ContextStampFilter` from
  obs/events.py is installed at the LOGGER level, so every record emitted
  inside a `bind(rid=..., node=..., epoch=...)` scope carries the bound
  identity — through every handler, including foreign ones — and the
  console/file format renders it as a ` [rid=... node=...]` suffix.
"""

from __future__ import annotations

import logging
import os
import sys
from pathlib import Path
from typing import Optional

_LOGGER_NAME = "dnet_tpu"
_configured = False


class ProfileFilter(logging.Filter):
    """Drop `[PROFILE]` lines unless profiling is enabled.

    Gating is resolved PER RECORD via `obs_enabled()` — not frozen at
    setup time — so an env flip mid-process (config.env_flag reads
    through the settings cache) can never desync this filter from the
    metrics registry's own gate.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        msg = record.getMessage()
        if "[PROFILE]" not in msg:
            return True
        from dnet_tpu.obs import obs_enabled

        return obs_enabled()


def setup_logger(
    role: Optional[str] = None,
    level: Optional[str] = None,
    log_dir: Optional[Path] = None,
    to_file: Optional[bool] = None,
) -> logging.Logger:
    """Configure and return the process-wide logger.

    role: "api" or "shard"; file name is dnet-api.log / dnet-shard-<pid>.log.
    """
    global _configured
    logger = logging.getLogger(_LOGGER_NAME)
    # An explicit role/level call reconfigures (a bare get_logger() at import
    # time must not lock out the CLI's later role-specific setup).
    explicit = role is not None or level is not None
    if _configured and not explicit:
        return logger
    # remove only the handlers THIS function installed; foreign handlers
    # (TUI live feed, test capture) survive reconfiguration
    for h in list(logger.handlers):
        if getattr(h, "_dnet_owned", False):
            logger.removeHandler(h)

    from dnet_tpu.config import get_settings
    from dnet_tpu.obs.events import ContextStampFilter

    s = get_settings()
    level = level or s.log.level
    log_dir = log_dir or s.log.dir
    to_file = s.log.to_file if to_file is None else to_file

    logger.setLevel(level.upper())
    logger.propagate = False
    # logger-level stamp: every record through any handler carries the
    # bound rid/node/epoch/tick (obs/events.py bind), exactly once
    if not any(isinstance(f, ContextStampFilter) for f in logger.filters):
        logger.addFilter(ContextStampFilter())
    fmt = logging.Formatter(
        "%(asctime)s %(levelname)-7s %(name)s%(ctx)s %(message)s",
        datefmt="%H:%M:%S",
    )
    console = logging.StreamHandler(sys.stderr)
    console.setFormatter(fmt)
    console.addFilter(ProfileFilter())
    console._dnet_owned = True  # type: ignore[attr-defined]
    logger.addHandler(console)

    if to_file and role:
        try:
            log_dir.mkdir(parents=True, exist_ok=True)
            name = (
                "dnet-api.log" if role == "api" else f"dnet-shard-{os.getpid()}.log"
            )
            fh = logging.FileHandler(log_dir / name)
            fh.setFormatter(fmt)
            fh.addFilter(ProfileFilter())
            fh._dnet_owned = True  # type: ignore[attr-defined]
            logger.addHandler(fh)
        except OSError:
            logger.warning("could not open log file in %s", log_dir)

    _configured = True
    return logger


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        return setup_logger()
    return logger
