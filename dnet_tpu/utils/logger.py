"""Central logger with [PROFILE] gating and per-process file logs.

Reference behavior: src/dnet/utils/logger.py:53-112 — a single "dnet" logger,
env-driven level, `[PROFILE]`-tagged lines filtered out unless profiling is
enabled, and per-process file handlers (api vs shard-PID names).
"""

from __future__ import annotations

import logging
import os
import sys
from pathlib import Path
from typing import Optional

_LOGGER_NAME = "dnet_tpu"
_configured = False


class ProfileFilter(logging.Filter):
    """Drop `[PROFILE]` lines unless profiling is enabled."""

    def __init__(self, enabled: bool) -> None:
        super().__init__()
        self.enabled = enabled

    def filter(self, record: logging.LogRecord) -> bool:
        if self.enabled:
            return True
        msg = record.getMessage()
        return "[PROFILE]" not in msg


def setup_logger(
    role: Optional[str] = None,
    level: Optional[str] = None,
    log_dir: Optional[Path] = None,
    to_file: Optional[bool] = None,
) -> logging.Logger:
    """Configure and return the process-wide logger.

    role: "api" or "shard"; file name is dnet-api.log / dnet-shard-<pid>.log.
    """
    global _configured
    logger = logging.getLogger(_LOGGER_NAME)
    # An explicit role/level call reconfigures (a bare get_logger() at import
    # time must not lock out the CLI's later role-specific setup).
    explicit = role is not None or level is not None
    if _configured and not explicit:
        return logger
    for h in list(logger.handlers):
        logger.removeHandler(h)

    from dnet_tpu.config import get_settings
    from dnet_tpu.obs import obs_enabled

    s = get_settings()
    level = level or s.log.level
    log_dir = log_dir or s.log.dir
    to_file = s.log.to_file if to_file is None else to_file
    # one gating truth shared with the metrics/recorder layer (dnet_tpu.obs):
    # the [PROFILE] filter and the registry can never disagree
    profile_on = obs_enabled()

    logger.setLevel(level.upper())
    logger.propagate = False
    fmt = logging.Formatter(
        "%(asctime)s %(levelname)-7s %(name)s %(message)s", datefmt="%H:%M:%S"
    )
    console = logging.StreamHandler(sys.stderr)
    console.setFormatter(fmt)
    console.addFilter(ProfileFilter(profile_on))
    logger.addHandler(console)

    if to_file and role:
        try:
            log_dir.mkdir(parents=True, exist_ok=True)
            name = (
                "dnet-api.log" if role == "api" else f"dnet-shard-{os.getpid()}.log"
            )
            fh = logging.FileHandler(log_dir / name)
            fh.setFormatter(fmt)
            fh.addFilter(ProfileFilter(profile_on))
            logger.addHandler(fh)
        except OSError:
            logger.warning("could not open log file in %s", log_dir)

    _configured = True
    return logger


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        return setup_logger()
    return logger
