"""Static discovery via hostfile (the CI workhorse in the reference).

Two formats are accepted, mirroring the reference's `load_hostfile`
(tests/test_static_discovery.py:13-60 in /root/reference):

1. SSH-style lines:  ``<instance> <host> <http_port> <grpc_port> [manager]``
2. JSON: ``[{"instance": ..., "host": ..., "http_port": ..., "grpc_port": ...,
   "is_manager": false, "slice_id": 0, "chip_count": 1}, ...]``
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List

from dnet_tpu.core.types import DeviceInfo


def load_hostfile(path: str | Path) -> List[DeviceInfo]:
    text = Path(path).read_text().strip()
    if not text:
        return []
    if text.lstrip().startswith(("[", "{")):
        return _parse_json(text)
    return _parse_lines(text)


def _parse_json(text: str) -> List[DeviceInfo]:
    data = json.loads(text)
    if isinstance(data, dict):
        data = data.get("devices", [])
    devices = []
    for entry in data:
        devices.append(
            DeviceInfo(
                instance=entry["instance"],
                host=entry["host"],
                http_port=int(entry["http_port"]),
                grpc_port=int(entry["grpc_port"]),
                is_manager=bool(entry.get("is_manager", False)),
                slice_id=int(entry.get("slice_id", 0)),
                chip_count=int(entry.get("chip_count", 1)),
                chip_kind=entry.get("chip_kind", ""),
            )
        )
    return devices


def _parse_lines(text: str) -> List[DeviceInfo]:
    devices = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) < 4:
            raise ValueError(f"bad hostfile line: {line!r}")
        devices.append(
            DeviceInfo(
                instance=parts[0],
                host=parts[1],
                http_port=int(parts[2]),
                grpc_port=int(parts[3]),
                is_manager=len(parts) > 4 and parts[4].lower() in {"manager", "true", "1"},
            )
        )
    return devices


class StaticDiscovery:
    """Hostfile-backed peer table with the same surface as live discovery."""

    def __init__(self, devices: List[DeviceInfo]):
        self._devices = {d.instance: d for d in devices}

    @classmethod
    def from_hostfile(cls, path: str | Path) -> "StaticDiscovery":
        return cls(load_hostfile(path))

    def peers(self) -> List[DeviceInfo]:
        return list(self._devices.values())

    def get(self, instance: str) -> DeviceInfo | None:
        return self._devices.get(instance)

    def add(self, device: DeviceInfo) -> None:
        self._devices[device.instance] = device

    def remove(self, instance: str) -> None:
        self._devices.pop(instance, None)
