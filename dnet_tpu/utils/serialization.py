"""Tensor <-> wire bytes and canonical dtype maps.

The wire format is raw little-endian bytes plus (dtype, shape) carried in the
frame header — same scheme as the reference (src/dnet/utils/serialization.py:
13-123, src/dnet/core/tensor.py:6-48) but numpy/ml_dtypes-based: bfloat16 is a
first-class dtype here (TPU-native) rather than a uint16 bit-shift fallback.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

# Canonical dtype-name map (wire name -> numpy dtype).
_WIRE_DTYPES: dict[str, np.dtype] = {
    "float32": np.dtype(np.float32),
    "float16": np.dtype(np.float16),
    "bfloat16": np.dtype(ml_dtypes.bfloat16),
    "float64": np.dtype(np.float64),
    "int8": np.dtype(np.int8),
    "uint8": np.dtype(np.uint8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "bool": np.dtype(np.bool_),
    "float8_e4m3": np.dtype(ml_dtypes.float8_e4m3fn),
    "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
}

_ALIASES = {
    "f32": "float32",
    "f16": "float16",
    "bf16": "bfloat16",
    "f64": "float64",
    "i8": "int8",
    "u8": "uint8",
    "i16": "int16",
    "i32": "int32",
    "i64": "int64",
    "BF16": "bfloat16",
    "F16": "float16",
    "F32": "float32",
    "F64": "float64",
    "I8": "int8",
    "I16": "int16",
    "I32": "int32",
    "I64": "int64",
    "U8": "uint8",
    "BOOL": "bool",
    "F8_E4M3": "float8_e4m3",
    "F8_E5M2": "float8_e5m2",
}


def canonical_dtype_name(name: str) -> str:
    return _ALIASES.get(name, name)


def numpy_dtype(name: str) -> np.dtype:
    canon = canonical_dtype_name(name)
    if canon not in _WIRE_DTYPES:
        raise ValueError(f"unsupported wire dtype: {name!r}")
    return _WIRE_DTYPES[canon]


def jax_dtype(name: str) -> jnp.dtype:
    return jnp.dtype(numpy_dtype(name))


def dtype_name(dtype) -> str:
    """Canonical wire name for a numpy/jax dtype."""
    nd = np.dtype(dtype)
    for name, cand in _WIRE_DTYPES.items():
        if cand == nd:
            return name
    raise ValueError(f"unsupported dtype: {dtype!r}")


def tensor_to_bytes(x, wire_dtype: str | None = None) -> Tuple[bytes, str, Tuple[int, ...]]:
    """Serialize a jax/numpy array to (payload, dtype_name, shape).

    Casts to `wire_dtype` first when given (the decode-path hop casts
    activations to the configured wire dtype — reference core/tensor.py:26).
    """
    if isinstance(x, jax.Array):
        x = np.asarray(jax.device_get(x))
    else:
        x = np.asarray(x)
    if wire_dtype is not None:
        target = numpy_dtype(wire_dtype)
        if x.dtype != target:
            x = x.astype(target)
    x = np.ascontiguousarray(x)
    return x.tobytes(), dtype_name(x.dtype), tuple(x.shape)


def bytes_to_tensor(
    payload: bytes | memoryview, dtype: str, shape: Sequence[int]
) -> np.ndarray:
    nd = numpy_dtype(dtype)
    expected = int(np.prod(shape)) * nd.itemsize if shape else nd.itemsize
    if len(payload) != expected:
        raise ValueError(
            f"payload size mismatch: got {len(payload)} bytes, "
            f"expected {expected} for {dtype}{tuple(shape)}"
        )
    arr = np.frombuffer(payload, dtype=nd)
    return arr.reshape(tuple(shape))


def bytes_to_device(payload: bytes, dtype: str, shape: Sequence[int], device=None):
    """Deserialize straight onto a device (single host->HBM copy)."""
    host = bytes_to_tensor(payload, dtype, shape)
    return jax.device_put(host, device)
