"""ctypes wrapper over the native host store (native/hoststore.cpp).

The disk->host-DRAM half of weight streaming, native like the reference's
(src/dnet/utils/layer_manager.py drives libc madvise; its repack/mmap IO is
the performance-critical native path).  Provides:

- NativeSafetensors: one mmap per .safetensors file with a self-parsed
  header (8-byte LE length + JSON index — the same structure the reference
  parses at src/dnet/utils/model.py:388-417), zero-copy numpy views per
  tensor, and per-tensor-span madvise prefetch/release.
- graceful degradation: if g++ or the platform is unavailable the importers
  fall back to the pure-Python safetensors path (`available()` gates use).

Page-cache streaming protocol (mirrors layer_manager modes):
  prefetch(names, sync=False)  -> WILLNEED + background page-touch, so the
                                  next window's disk reads overlap compute
  release(names)               -> DONTNEED evicted windows' pages
"""

from __future__ import annotations

import ctypes
import json
import struct
import subprocess
import threading
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from dnet_tpu.utils.logger import get_logger

log = get_logger()

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_SRC = _NATIVE_DIR / "hoststore.cpp"
_LIB = _NATIVE_DIR / "libdnethost.so"
_build_lock = threading.Lock()
_lib = None
_lib_failed = False


def ensure_built(force: bool = False) -> Path:
    """Compile the host-store library if missing/stale (g++ is baked in)."""
    with _build_lock:
        if (
            not force
            and _LIB.is_file()
            and _LIB.stat().st_mtime >= _SRC.stat().st_mtime
        ):
            return _LIB
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            "-o", str(_LIB), str(_SRC), "-lpthread",
        ]
        log.info("building native host store: %s", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native host store build failed:\n{proc.stderr.strip()}"
            )
        return _LIB


def _load():
    lib = ctypes.CDLL(str(ensure_built()))
    lib.hs_open.argtypes = [ctypes.c_char_p]
    lib.hs_open.restype = ctypes.c_int
    lib.hs_close.argtypes = [ctypes.c_int]
    lib.hs_size.argtypes = [ctypes.c_int]
    lib.hs_size.restype = ctypes.c_uint64
    lib.hs_addr.argtypes = [ctypes.c_int]
    lib.hs_addr.restype = ctypes.c_void_p
    for f in (lib.hs_prefetch, lib.hs_prefetch_async, lib.hs_release):
        f.argtypes = [ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64]
        f.restype = ctypes.c_int
    lib.hs_read.argtypes = [
        ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p,
    ]
    lib.hs_read.restype = ctypes.c_int
    lib.hs_pending.restype = ctypes.c_int
    return lib


def _get_lib():
    global _lib, _lib_failed
    if _lib is None and not _lib_failed:
        try:
            _lib = _load()
        except Exception as exc:  # missing toolchain / unsupported platform
            _lib_failed = True
            log.warning("native host store unavailable, using python IO: %s", exc)
    return _lib


def available() -> bool:
    return _get_lib() is not None


_ST_DTYPES = {
    "F64": np.dtype(np.float64), "F32": np.dtype(np.float32),
    "F16": np.dtype(np.float16), "I64": np.dtype(np.int64),
    "I32": np.dtype(np.int32), "I16": np.dtype(np.int16),
    "I8": np.dtype(np.int8), "U8": np.dtype(np.uint8),
    "U16": np.dtype(np.uint16), "U32": np.dtype(np.uint32),
    "U64": np.dtype(np.uint64), "BOOL": np.dtype(np.bool_),
}


def _np_dtype(st_dtype: str) -> np.dtype:
    if st_dtype == "BF16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return _ST_DTYPES[st_dtype]
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {st_dtype!r}") from None


class NativeSafetensors:
    """One safetensors file: native mmap + parsed header + zero-copy views."""

    def __init__(self, path: str | Path):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native host store unavailable")
        self._lib = lib
        self.path = Path(path)
        self._h = lib.hs_open(str(self.path).encode())
        if self._h < 0:
            raise OSError(f"hs_open failed for {self.path}")
        self.size = int(lib.hs_size(self._h))
        # header: u64 LE json length, then the json index; tensor offsets
        # are relative to the data section that follows the header
        hdr_len_buf = (ctypes.c_char * 8)()
        if lib.hs_read(self._h, 0, 8, hdr_len_buf) != 0:
            raise OSError(f"short read on {self.path}")
        (hdr_len,) = struct.unpack("<Q", hdr_len_buf.raw)
        if 8 + hdr_len > self.size:
            raise ValueError(f"corrupt safetensors header in {self.path}")
        hdr_buf = ctypes.create_string_buffer(hdr_len)
        lib.hs_read(self._h, 8, hdr_len, hdr_buf)
        header = json.loads(hdr_buf.raw.decode("utf-8"))
        header.pop("__metadata__", None)
        self._data0 = 8 + hdr_len
        # name -> (abs_offset, nbytes, dtype, shape)
        self.tensors: Dict[str, Tuple[int, int, np.dtype, Tuple[int, ...]]] = {}
        for name, info in header.items():
            a, b = info["data_offsets"]
            self.tensors[name] = (
                self._data0 + a,
                b - a,
                _np_dtype(info["dtype"]),
                tuple(info["shape"]),
            )
        base = lib.hs_addr(self._h)
        buf = (ctypes.c_char * self.size).from_address(base)
        self._view = np.frombuffer(buf, dtype=np.uint8)
        self._view.flags.writeable = False

    def keys(self) -> List[str]:
        return list(self.tensors)

    def tensor(self, name: str) -> np.ndarray:
        """Zero-copy read-only view into the mapped file."""
        off, nbytes, dtype, shape = self.tensors[name]
        flat = self._view[off : off + nbytes]
        return flat.view(dtype).reshape(shape)

    def span(self, name: str) -> Tuple[int, int]:
        off, nbytes, _, _ = self.tensors[name]
        return off, nbytes

    def _coalesced(self, names: Iterable[str]) -> List[Tuple[int, int]]:
        """Merge tensor spans into maximal runs (the reference coalesces
        per-file spans before madvise, layer_manager.py:160-186)."""
        spans = sorted(self.span(n) for n in names)
        out: List[Tuple[int, int]] = []
        for off, nbytes in spans:
            if out and off <= out[-1][0] + out[-1][1] + 4096:
                prev_off, prev_len = out[-1]
                out[-1] = (prev_off, max(prev_len, off + nbytes - prev_off))
            else:
                out.append((off, nbytes))
        return out

    def prefetch(self, names: Iterable[str], sync: bool = False) -> None:
        fn = self._lib.hs_prefetch if sync else self._lib.hs_prefetch_async
        for off, nbytes in self._coalesced(names):
            fn(self._h, off, nbytes)

    def release(self, names: Iterable[str]) -> None:
        for off, nbytes in self._coalesced(names):
            self._lib.hs_release(self._h, off, nbytes)

    def pending(self) -> int:
        return int(self._lib.hs_pending())

    def close(self) -> None:
        if self._h >= 0:
            # the numpy view aliases the mapping; drop it before munmap
            self._view = None
            self._lib.hs_close(self._h)
            self._h = -1

    def __del__(self):  # best-effort; explicit close preferred
        try:
            self.close()
        except Exception:
            pass
