"""Network address helpers (reference: src/dnet/utils/network.py)."""

from __future__ import annotations

import socket
from typing import Iterable


def primary_ip(peer_hosts: Iterable[str] = ()) -> str:
    """Best-effort address peers can reach us on.

    If every peer is loopback, loopback is correct.  Otherwise use the
    UDP-connect trick against the first non-loopback peer (no packets sent)
    to find the outbound interface address.
    """
    peers = [h for h in peer_hosts if h]
    non_loop = [h for h in peers if h not in ("127.0.0.1", "localhost", "::1")]
    if peers and not non_loop:
        return "127.0.0.1"
    target = non_loop[0] if non_loop else "8.8.8.8"
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((target, 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
