"""UDP-broadcast LAN discovery (ctypes wrapper over native/discovery.cpp).

The native analog of the reference's Rust dnet-p2p (loaded the same way the
reference loads its lib: cli/shard.py:34 `AsyncDnetP2P("lib/dnet-p2p/lib")`).
The shared library is built on demand with g++ and cached next to the
source; `UdpDiscovery` exposes the same peer-table surface as
`StaticDiscovery`, so the API node's ClusterManager is agnostic.
"""

from __future__ import annotations

import ctypes
import json
import subprocess
import threading
from pathlib import Path
from typing import List, Optional

from dnet_tpu.core.types import DeviceInfo
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_SRC = _NATIVE_DIR / "discovery.cpp"
_LIB = _NATIVE_DIR / "libdnetdisc.so"
_build_lock = threading.Lock()


def ensure_built(force: bool = False) -> Path:
    """Compile the discovery library if missing/stale (g++ is baked in)."""
    with _build_lock:
        if (
            not force
            and _LIB.is_file()
            and _LIB.stat().st_mtime >= _SRC.stat().st_mtime
        ):
            return _LIB
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            "-o", str(_LIB), str(_SRC), "-lpthread",
        ]
        log.info("building native discovery: %s", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native discovery build failed:\n{proc.stderr.strip()}"
            )
        return _LIB


def _load():
    lib = ctypes.CDLL(str(ensure_built()))
    lib.dnet_disc_start.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
    ]
    lib.dnet_disc_start.restype = ctypes.c_int
    lib.dnet_disc_update.argtypes = [ctypes.c_char_p]
    lib.dnet_disc_peers.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.dnet_disc_peers.restype = ctypes.c_int
    lib.dnet_disc_stop.argtypes = []
    return lib


class UdpDiscovery:
    """Announce this node + maintain a live LAN peer table.

    One instance per process (the native lib holds process-global state,
    like the reference's in-process Rust lib).
    """

    def __init__(
        self,
        instance: str,
        http_port: int,
        grpc_port: int,
        is_manager: bool = False,
        slice_id: int = 0,
        udp_port: int = 58899,
        target_addr: str = "255.255.255.255",
        interval_ms: int = 500,
        ttl_s: float = 5.0,
        cluster: str = "default",
    ) -> None:
        self.instance = instance
        self.cluster = cluster
        self._lib = _load()
        self._self = {
            "instance": instance,
            "cluster": cluster,  # scopes membership: two LANs, two clusters
            "http_port": str(http_port),
            "grpc_port": str(grpc_port),
            "is_manager": "1" if is_manager else "0",
            "slice_id": str(slice_id),
        }
        rc = self._lib.dnet_disc_start(
            json.dumps(self._self, separators=(",", ":")).encode(),
            target_addr.encode(),
            udp_port,
            interval_ms,
            ctypes.c_double(ttl_s),
        )
        if rc == 1:
            raise RuntimeError("discovery already running in this process")
        if rc != 0:
            raise RuntimeError(
                f"discovery could not bind UDP port {udp_port} "
                "(already in use without SO_REUSEPORT?)"
            )

    def peers(self) -> List[DeviceInfo]:
        # size + fill must agree even if the table grows in between: retry
        # with the newly reported size until it fits
        needed = self._lib.dnet_disc_peers(None, 0)
        for _ in range(5):
            buf = ctypes.create_string_buffer(needed)
            got = self._lib.dnet_disc_peers(buf, needed)
            if got <= needed:
                break
            needed = got
        try:
            raw = json.loads(buf.value.decode() or "[]")
        except json.JSONDecodeError:
            log.warning("malformed peer table from native discovery")
            return []
        out = []
        for p in raw:
            if p.get("cluster", "default") != self.cluster:
                continue  # different deployment sharing the LAN/port
            try:
                out.append(
                    DeviceInfo(
                        instance=p["instance"],
                        host=p.get("addr", ""),
                        http_port=int(p["http_port"]),
                        grpc_port=int(p["grpc_port"]),
                        is_manager=p.get("is_manager") == "1",
                        slice_id=int(p.get("slice_id", 0)),
                    )
                )
            except (KeyError, ValueError):
                continue
        return out

    def get(self, instance: str) -> Optional[DeviceInfo]:
        for d in self.peers():
            if d.instance == instance:
                return d
        return None

    def stop(self) -> None:
        self._lib.dnet_disc_stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
