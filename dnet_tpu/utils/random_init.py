"""Random parameter initialization (bench/dryrun/test fixtures).

Builds the same stacked-window + edge param pytrees the checkpoint loader
produces, but from a config alone — no weights on disk.  Zero-egress
benchmarking runs on synthetic weights with real model shapes.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from dnet_tpu.models.base import ModelConfig

LLAMA_3_2_1B_CONFIG = {
    "model_type": "llama",
    "vocab_size": 128256,
    "hidden_size": 2048,
    "intermediate_size": 8192,
    "num_hidden_layers": 16,
    "num_attention_heads": 32,
    "num_key_value_heads": 8,
    "head_dim": 64,
    "rms_norm_eps": 1e-5,
    "rope_theta": 500000.0,
    "rope_scaling": {
        "rope_type": "llama3",
        "factor": 32.0,
        "low_freq_factor": 1.0,
        "high_freq_factor": 4.0,
        "original_max_position_embeddings": 8192,
    },
    "max_position_embeddings": 131072,
    "tie_word_embeddings": True,
}

LLAMA_3_8B_CONFIG = {
    "model_type": "llama",
    "vocab_size": 128256,
    "hidden_size": 4096,
    "intermediate_size": 14336,
    "num_hidden_layers": 32,
    "num_attention_heads": 32,
    "num_key_value_heads": 8,
    "head_dim": 128,
    "rms_norm_eps": 1e-5,
    "rope_theta": 500000.0,
    "max_position_embeddings": 8192,
    "tie_word_embeddings": False,
}


def random_llama_params(
    cfg: ModelConfig,
    layers: Sequence[int],
    dtype: str = "bfloat16",
    seed: int = 0,
) -> Tuple[Dict, Dict]:
    """(stacked window params, edge params) with real shapes, random values."""
    L = len(list(layers))
    D, F = cfg.hidden_size, cfg.intermediate_size
    H, KVH, Hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    V = cfg.vocab_size
    dt = jnp.dtype(dtype)
    key = jax.random.key(seed)
    ks = iter(jax.random.split(key, 16))

    def w(*shape, scale=0.02):
        return (jax.random.normal(next(ks), shape, dtype=jnp.float32) * scale).astype(dt)

    window = {
        "attn_norm": jnp.ones((L, D), dtype=dt),
        "wq": w(L, D, H * Hd),
        "wk": w(L, D, KVH * Hd),
        "wv": w(L, D, KVH * Hd),
        "wo": w(L, H * Hd, D),
        "mlp_norm": jnp.ones((L, D), dtype=dt),
        "w_gate": w(L, D, F),
        "w_up": w(L, D, F),
        "w_down": w(L, F, D),
    }
    edge = {
        "embed": {"weight": w(V, D)},
        "final_norm": {"weight": jnp.ones((D,), dtype=dt)},
    }
    if not cfg.tie_word_embeddings:
        edge["lm_head"] = {"weight": w(D, V)}
    return window, edge
