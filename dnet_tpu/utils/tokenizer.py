"""Tokenizer access: HF tokenizer from a local dir, byte-level fallback.

The reference loads tokenizers via mlx_lm on the API node
(src/dnet/api/model_manager.py:169-182).  Here: `transformers.AutoTokenizer`
when tokenizer files exist locally; otherwise a self-contained byte-level
tokenizer (vocab 256 + BOS/EOS) so tests and air-gapped runs never need the
Hub.  Both expose the same minimal surface: encode / decode / chat template /
eos_token_ids, plus an incremental `Detokenizer` for SSE streaming.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence


class ByteTokenizer:
    """Byte-level tokenizer: token = byte value; 256=BOS, 257=EOS."""

    vocab_size = 258
    bos_token_id = 256
    eos_token_id = 257

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_token_id] if add_bos else []) + ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")

    @property
    def eos_token_ids(self) -> set[int]:
        return {self.eos_token_id}

    def apply_chat_template(self, messages: List[dict], add_generation_prompt: bool = True) -> str:
        parts = [f"<|{m['role']}|>\n{m['content']}" for m in messages]
        if add_generation_prompt:
            parts.append("<|assistant|>\n")
        return "\n".join(parts)


class HFTokenizer:
    """Thin wrapper over transformers.AutoTokenizer (local files only)."""

    def __init__(self, model_dir: str | Path):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(str(model_dir), local_files_only=True)
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    @property
    def eos_token_ids(self) -> set[int]:
        ids = set()
        if self._tok.eos_token_id is not None:
            ids.add(int(self._tok.eos_token_id))
        # llama-3 style generation config may add more; config.json eos can be a list
        extra = getattr(self._tok, "additional_eos_token_ids", None)
        if extra:
            ids.update(int(i) for i in extra)
        return ids

    def apply_chat_template(self, messages: List[dict], add_generation_prompt: bool = True) -> str:
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=add_generation_prompt
            )
        parts = [f"<|{m['role']}|>\n{m['content']}" for m in messages]
        if add_generation_prompt:
            parts.append("<|assistant|>\n")
        return "\n".join(parts)


def load_tokenizer(model_dir: Optional[str | Path]):
    """HF tokenizer if the dir has tokenizer files, else ByteTokenizer.

    When tokenizer files exist but fail to load, that is an error — silently
    byte-encoding against a real model's vocab would corrupt every request.
    """
    if model_dir:
        d = Path(model_dir)
        if any(
            (d / f).is_file()
            for f in ("tokenizer.json", "tokenizer.model", "tokenizer_config.json")
        ):
            return HFTokenizer(d)
    return ByteTokenizer()


class Detokenizer:
    """Incremental detokenizer for SSE streaming: feed token ids, get text
    deltas, holding back bytes that may be a partial multi-byte char.

    Reference analog: the detokenizer incremental-delta loop in
    src/dnet/api/inference.py:179-212.
    """

    TAIL = 16  # ids kept in the working window (enough for any multi-byte char run)
    HARD_CAP = 128  # force-finalize beyond this: the window must stay bounded

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._ids: List[int] = []  # working tail window only — O(1) per token
        self._done = ""  # text already finalized out of the window
        self._emitted_len = 0  # chars emitted so far (over done + window text)

    def _window_text(self) -> str:
        full = self._tok.decode(self._ids)
        return full[:-1] if full.endswith("�") else full

    def add(self, token_id: int) -> str:
        self._ids.append(int(token_id))
        window_text = None  # reuse decodes from the finalize pass when set
        if len(self._ids) > 2 * self.TAIL:
            # Finalize the head of the window.  The finalized text is taken
            # from the FULL window decode (full[:-len(rest_text)]), so
            # context-dependent decoding (sentencepiece leading-space
            # stripping) cannot drop characters: the suffix check proves the
            # kept ids decode to a literal suffix of the in-context text.  A
            # boundary that splits a multi-byte char fails the check (rest
            # decodes to a replacement char), so several consecutive
            # boundaries are tried — a char spans <= 4 ids, one of them is
            # clean.  A hard cap (exhaustive boundary search, then flush)
            # keeps the window — and per-token decode cost — bounded even
            # for a pathological tokenizer.
            full = self._tok.decode(self._ids)
            limit = len(self._ids) - self.TAIL
            over_cap = len(self._ids) > self.HARD_CAP
            tries = range(self.TAIL, limit if over_cap else min(self.TAIL + 4, limit))
            for j in tries:
                rest_text = self._tok.decode(self._ids[j:])
                if rest_text and full.endswith(rest_text):
                    self._done += full[: len(full) - len(rest_text)]
                    self._ids = self._ids[j:]
                    window_text = rest_text
                    break
            else:
                window_text = full
                if over_cap:
                    self._done += full
                    self._ids = []
                    window_text = ""
        if window_text is None:
            window_text = self._tok.decode(self._ids)
        if window_text.endswith("�"):
            window_text = window_text[:-1]
        total = self._done + window_text
        delta = total[self._emitted_len:]
        if delta:
            self._emitted_len = len(total)
        return delta

    def flush(self) -> str:
        total = self._done + self._tok.decode(self._ids)
        delta = total[self._emitted_len:]
        self._emitted_len = len(total)
        return delta
