"""Version compatibility shims for jax APIs the codebase leans on.

The serving code targets the stable `jax.shard_map` entry point and the
varying-mesh-axes type system (`lax.pcast(..., to="varying")`, checked by
shard_map's check_vma).  Older jax releases (<= 0.4.x) ship shard_map as
`jax.experimental.shard_map.shard_map` with the legacy `check_rep`
replication checker and no `pcast`.  Resolving the callables HERE — once,
at import — keeps every mesh program builder (parallel/ring.py,
parallel/pipelined.py, parallel/shard_mesh.py, ops/ring_attention.py) free
of per-call version probes.

On old jax the shim disables `check_rep` (the legacy checker rejects the
collectives the ring programs use to describe per-stage-varying values)
and `pcast_varying` becomes the identity — the annotation has no runtime
semantics, it only informs the checker being disabled.
"""

from __future__ import annotations

import jax
from jax import lax

try:  # jax >= 0.5: stable top-level entry point with check_vma
    shard_map = jax.shard_map
    _HAS_PCAST = hasattr(lax, "pcast")
except AttributeError:  # older jax: experimental module + check_rep
    from functools import partial

    from jax.experimental.shard_map import shard_map as _exp_shard_map

    shard_map = partial(_exp_shard_map, check_rep=False)
    _HAS_PCAST = False


# whether ShapeDtypeStruct carries a vma declaration (jax >= 0.6 pallas
# under check_vma); without it there is no checker to satisfy, so callers
# simply drop the kwarg
try:
    jax.ShapeDtypeStruct((1,), "float32", vma=frozenset())
    SDS_HAS_VMA = True
except TypeError:
    SDS_HAS_VMA = False


def manual_axis_names() -> frozenset:
    """Names of the manual mesh axes of the CURRENT trace (empty outside
    shard_map).  On old jax every value inside shard_map is device-varying
    over every manual axis, so this is the conservative vma for all of
    them; on current jax prefer per-array `jax.typeof(x).vma`."""
    import jax.core as jcore

    try:
        return frozenset(jcore.unsafe_get_axis_names_DO_NOT_USE())
    except Exception:
        return frozenset()


def axis_size(axis_name) -> int:
    """Static size of a mesh axis from inside shard_map (`lax.axis_size`
    on current jax; `jax.core.axis_frame` returns the same int on 0.4.x)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    import jax.core as jcore

    return jcore.axis_frame(axis_name)


def pcast_varying(x, axis_name):
    """Mark a replicated value as varying over `axis_name` (tuple ok) for
    shard_map's vma checker; identity on jax without the vma type system."""
    if _HAS_PCAST:
        return lax.pcast(x, axis_name, to="varying")
    return x


__all__ = ["axis_size", "pcast_varying", "shard_map"]
