"""Llama-family ring model (Llama 2/3.x, Hermes, etc.).

TPU-first re-design of the reference's `LlamaRingModel`
(src/dnet/core/models/llama.py:41-117): layers are stacked along a leading
axis and applied with one `lax.scan` per window (one XLA program per window
size, MXU-sized matmuls), weights live as (in, out)-oriented matrices so the
hot path is `x @ W` with no transposes, and rotary tables are closed over as
constants.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnet_tpu.models.base import ModelConfig, RingModel
from dnet_tpu.ops.attention import cached_attend
from dnet_tpu.parallel.tp_collectives import tp_all_reduce
from dnet_tpu.ops.norms import rms_norm
from dnet_tpu.ops.quant import dq, out_dim
from dnet_tpu.ops.rope import apply_rope, rope_frequencies


class LlamaRingModel(RingModel):
    model_type = "llama"
    # the standard norm->qkv->rope->cached_attend->o-proj layer body: the
    # attention half swaps cleanly for the ragged paged program
    supports_paged_attend = True

    def __init__(self, config: ModelConfig, layers):
        super().__init__(config, layers)
        inv_freq, self.rope_scale = rope_frequencies(
            config.head_dim,
            config.rope_theta,
            config.rope_scaling,
            config.max_position_embeddings,
        )
        self.inv_freq = jnp.asarray(inv_freq)

    # ---- pure compute (embed/lm_project inherited quant-aware) ---------
    def _qk_transform(self, p: dict, q: jnp.ndarray, k: jnp.ndarray):
        """Pre-RoPE q/k hook; identity for llama (qwen3 adds per-head norms)."""
        return q, k

    def _layer(self, p: dict, x: jnp.ndarray, kvs: dict, pos, mask, tp_axis=None, kv_commit=None, sp_axis=None, attend_fn=None):
        """One decoder layer.  Works on full params or tensor-parallel slices:
        local head counts come from the (possibly sharded) param shapes, and
        `tp_axis` inserts the two Megatron-style psums (after o-proj and
        down-proj) when running inside shard_map.  kv_commit (scalar bool)
        gates the cache write O(T)-cheaply — a pipeline rank processing a
        not-its-turn copy must not pollute its cache.  kvs is this layer's
        cache-slice dict (may carry int8/int4 quant scales).  sp_axis: the
        KV sequence axis is sharded over this mesh axis (ring attention /
        distributed flash-decoding); `mask` is then the [T, S_local]
        validity mask against this rank's shard."""
        cfg = self.config
        B, T, D = x.shape
        Hd = cfg.head_dim
        H = out_dim(p["wq"]) // Hd  # local heads (== cfg heads / tp)
        KVH = out_dim(p["wk"]) // Hd

        h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
        # qkv biases are present only for families that ship them (qwen2);
        # the per-family param dict is homogeneous so `in p` is static
        q = h @ dq(p["wq"])
        k = h @ dq(p["wk"])
        v = h @ dq(p["wv"])
        if "bq" in p:
            q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
        q = q.reshape(B, T, H, Hd)
        k = k.reshape(B, T, KVH, Hd)
        v = v.reshape(B, T, KVH, Hd)
        q, k = self._qk_transform(p, q, k)  # subclass hook (qwen3 q/k norms)
        positions = pos + jnp.arange(T)
        q = apply_rope(q, positions, self.inv_freq, self.rope_scale)
        k = apply_rope(k, positions, self.inv_freq, self.rope_scale)
        if attend_fn is not None:
            # ragged paged attention (ops/paged_attention.py): the caller
            # owns both the cache write (block append) and the attention
            # read; kvs is this layer's pool slice dict, passed through so
            # the hook can read it and return what the scan should stack
            attn, kvs = attend_fn(q, k, v, kvs)
        else:
            attn, kvs = cached_attend(
                q, k, v, kvs, pos, mask, kv_commit=kv_commit, sp_axis=sp_axis,
                causal=mask is None,
            )
        attn_out = attn.reshape(B, T, H * Hd) @ dq(p["wo"])
        if tp_axis is not None:
            # out-proj all-reduce: THE first of the two per-layer TP
            # collectives, routed through the quantizable seam (exact
            # psum for plain string axes, parallel/tp_collectives.py)
            attn_out = tp_all_reduce(attn_out, tp_axis)
        x = x + attn_out

        x = self._mlp_block(p, x, tp_axis)
        return x, kvs

    def _mlp_block(self, p: dict, x: jnp.ndarray, tp_axis=None) -> jnp.ndarray:
        """Post-attention FFN incl. the residual add; subclass hook (mixtral
        swaps in the sparse-MoE block)."""
        h = rms_norm(x, p["mlp_norm"], self.config.rms_norm_eps)
        gate = h @ dq(p["w_gate"])
        up = h @ dq(p["w_up"])
        mlp_out = (jax.nn.silu(gate) * up) @ dq(p["w_down"])
        if tp_axis is not None:
            # down-proj all-reduce: the second per-layer TP collective
            mlp_out = tp_all_reduce(mlp_out, tp_axis)
        return x + mlp_out

    def apply_window(
        self,
        window_params: dict,
        x: jnp.ndarray,
        kv: dict,
        pos: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        layer_kinds: Optional[jnp.ndarray] = None,
        tp_axis: Optional[str] = None,
        kv_commit=None,
        sp_axis: Optional[str] = None,
        t_real=None,  # full-length caches overwrite padding before reading
        attend_fn=None,
    ) -> Tuple[jnp.ndarray, dict]:
        # the causal predicate stays implicit (mask=None) under sp too:
        # cached_attend owns the rank-local sp mask (or the TPU split-K
        # flash-decode partials) — pre-building sp_causal_mask here would
        # make the kernel path unreachable

        def body(carry, per_layer):
            xc = carry
            p, kvs = per_layer
            xc, kvs = self._layer(
                p, xc, kvs, pos, mask, tp_axis=tp_axis, kv_commit=kv_commit,
                sp_axis=sp_axis, attend_fn=attend_fn,
            )
            return xc, kvs

        x, kv_out = lax.scan(body, x, (window_params, kv))
        return x, kv_out

    def normalize(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        return rms_norm(x, edge_params["final_norm"]["weight"], self.config.rms_norm_eps)

    # ---- weight mapping ----------------------------------------------
    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        def t(name: str) -> np.ndarray:
            return np.ascontiguousarray(raw[name].T)  # HF [out,in] -> (in,out)

        out = {
            "attn_norm": raw["input_layernorm.weight"],
            "wq": t("self_attn.q_proj.weight"),
            "wk": t("self_attn.k_proj.weight"),
            "wv": t("self_attn.v_proj.weight"),
            "wo": t("self_attn.o_proj.weight"),
            "mlp_norm": raw["post_attention_layernorm.weight"],
            "w_gate": t("mlp.gate_proj.weight"),
            "w_up": t("mlp.up_proj.weight"),
            "w_down": t("mlp.down_proj.weight"),
        }
        # keyed on checkpoint CONTENTS, not family: llama checkpoints with
        # attention_bias=true and qwen2/2.5 both ship qkv biases
        if "self_attn.q_proj.bias" in raw:
            out["bq"] = raw["self_attn.q_proj.bias"]
            out["bk"] = raw["self_attn.k_proj.bias"]
            out["bv"] = raw["self_attn.v_proj.bias"]
        return out

