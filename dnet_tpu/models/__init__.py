"""Model registry: HF `model_type` string -> RingModel subclass.

Reference: src/dnet/core/models/__init__.py:13-35 (subclass scan).
"""

from __future__ import annotations

from typing import Type

from dnet_tpu.models.base import ModelConfig, RingModel


def _all_subclasses(cls: type) -> list[type]:
    out = []
    for sub in cls.__subclasses__():
        out.append(sub)
        out.extend(_all_subclasses(sub))
    return out


def get_ring_model_cls(model_type: str) -> Type[RingModel]:
    # Import concrete models so subclasses are registered.
    from dnet_tpu.models import llama  # noqa: F401

    try:
        from dnet_tpu.models import qwen3  # noqa: F401
    except ImportError:
        pass
    try:
        from dnet_tpu.models import gpt_oss  # noqa: F401
    except ImportError:
        pass
    try:
        from dnet_tpu.models import deepseek_v2  # noqa: F401
    except ImportError:
        pass
    try:
        from dnet_tpu.models import mixtral  # noqa: F401
    except ImportError:
        pass
    try:
        from dnet_tpu.models import qwen2  # noqa: F401
    except ImportError:
        pass
    try:
        from dnet_tpu.models import qwen3_moe  # noqa: F401
    except ImportError:
        pass

    for sub in _all_subclasses(RingModel):
        if getattr(sub, "model_type", None) == model_type:
            return sub
    raise ValueError(f"unsupported model_type: {model_type!r}")


__all__ = ["ModelConfig", "RingModel", "get_ring_model_cls"]
