"""GPT-OSS-family ring model: MoE + alternating sliding/full attention + sinks.

Reference analog: src/dnet/core/models/gpt_oss.py (dual full/SWA masks,
GLOBAL-vs-LOCAL cache handling, MXFP4 desharding).  TPU-first design:

- Alternating layer kinds stay inside ONE `lax.scan`: both masks are built
  once per window and each layer selects by its kind scalar (kind rides the
  scan xs, so one compiled program serves both kinds).  KV is full-length
  with an SWA mask — trades the RotatingKVCache's memory saving for a single
  fused program; grouped scans can reclaim the memory later.
- MoE experts are computed densely and weighted by the router's scattered
  scores (zero for non-top-k => exact numerics) — MXU-friendly einsum over
  the expert dim; `tp_axis` shards the EXPERT dim, so tensor-parallel ranks
  are expert-parallel here and the psum over partial outputs is the routed sum.
- Attention sinks ride through ops.attention.attend(sinks=...).

Weights follow the HF dequantized layout (experts as [E, D, 2F]/[E, F, D]
with interleaved gate/up columns, clamped swiglu alpha=1.702, limit=7).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnet_tpu.models.base import ModelConfig, RingModel
from dnet_tpu.ops.attention import (
    cached_attend,
    causal_mask,
    sliding_window_mask,
    sp_causal_mask,
    sp_sliding_window_mask,
)
from dnet_tpu.ops.norms import rms_norm
from dnet_tpu.ops.quant import dq, lead_dim, out_dim
from dnet_tpu.ops.rope import apply_rope, rope_frequencies

ALPHA = 1.702
LIMIT = 7.0


class GptOssRingModel(RingModel):
    model_type = "gpt_oss"

    def __init__(self, config: ModelConfig, layers):
        super().__init__(config, layers)
        inv_freq, self.rope_scale = rope_frequencies(
            config.head_dim,
            config.rope_theta,
            config.rope_scaling,
            config.max_position_embeddings,
        )
        self.inv_freq = jnp.asarray(inv_freq)
        kinds = config.layer_types or ["full_attention"] * config.num_hidden_layers
        # kind per ASSIGNED layer (0=full, 1=sliding), aligned with the stack
        self.layer_kinds = jnp.asarray(
            [1 if kinds[a] == "sliding_attention" else 0 for a in self.layers],
            dtype=jnp.int32,
        )

    # ---- pure compute -------------------------------------------------
    def embed(self, edge_params: dict, tokens: jnp.ndarray) -> jnp.ndarray:
        return edge_params["embed"]["weight"][tokens]

    def _attention(self, p, x, kvs, pos, mask, tp_axis, kv_commit, sp_axis=None):
        cfg = self.config
        B, T, D = x.shape
        Hd = cfg.head_dim
        H = out_dim(p["wq"]) // Hd
        KVH = out_dim(p["wk"]) // Hd

        h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
        q = (h @ dq(p["wq"]) + p["bq"]).reshape(B, T, H, Hd)
        k = (h @ dq(p["wk"]) + p["bk"]).reshape(B, T, KVH, Hd)
        v = (h @ dq(p["wv"]) + p["bv"]).reshape(B, T, KVH, Hd)
        positions = pos + jnp.arange(T)
        q = apply_rope(q, positions, self.inv_freq, self.rope_scale)
        k = apply_rope(k, positions, self.inv_freq, self.rope_scale)
        attn, kvs = cached_attend(
            q, k, v, kvs, pos, mask,
            kv_commit=kv_commit, sp_axis=sp_axis, sinks=p["sinks"],
        )
        out = attn.reshape(B, T, H * Hd) @ dq(p["wo"])
        if tp_axis is not None:
            out = lax.psum(out, tp_axis)
        out = out + p["bo"]  # bias replicated: add once, after the psum
        return x + out, kvs

    def _moe(self, p, x, tp_axis):
        B, T, D = x.shape
        h = rms_norm(x, p["mlp_norm"], self.config.rms_norm_eps)
        flat = h.reshape(B * T, D)

        # router over the FULL expert set (router weights replicated)
        logits = flat @ p["router_w"] + p["router_b"]  # [N, E_total]
        k = self.config.num_experts_per_tok
        top_vals, top_idx = lax.top_k(logits, k)
        top_probs = jax.nn.softmax(top_vals.astype(jnp.float32), axis=-1).astype(flat.dtype)
        scores = jnp.zeros_like(logits).at[
            jnp.arange(flat.shape[0])[:, None], top_idx
        ].set(top_probs)

        # dense expert compute over the LOCAL expert slice (tp shards experts)
        E_local = lead_dim(p["gate_up"])
        gate_up = jnp.einsum("nd,edf->nef", flat, dq(p["gate_up"])) + p["gate_up_b"]
        gate = jnp.clip(gate_up[..., ::2], max=LIMIT)
        up = jnp.clip(gate_up[..., 1::2], min=-LIMIT, max=LIMIT)
        glu = gate * jax.nn.sigmoid(gate * ALPHA)
        inner = (up + 1.0) * glu  # [N, E_local, F]
        expert_out = jnp.einsum("nef,efd->ned", inner, dq(p["down"])) + p["down_b"]

        if tp_axis is not None:
            e_off = lax.axis_index(tp_axis) * E_local
            local_scores = lax.dynamic_slice_in_dim(scores, e_off, E_local, axis=1)
        else:
            local_scores = scores
        out = jnp.einsum("ned,ne->nd", expert_out, local_scores)
        if tp_axis is not None:
            out = lax.psum(out, tp_axis)
        return x + out.reshape(B, T, D)

    def apply_window(
        self,
        window_params: dict,
        x: jnp.ndarray,
        kv: dict,
        pos: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        layer_kinds: Optional[jnp.ndarray] = None,
        tp_axis: Optional[str] = None,
        kv_commit=None,
        sp_axis: Optional[str] = None,
    ) -> Tuple[jnp.ndarray, dict]:
        T, S = x.shape[1], kv["k"].shape[2]
        swa = self.config.sliding_window or (
            S * (1 if sp_axis is None else lax.psum(1, sp_axis))
        )
        if sp_axis is None:
            full_mask = causal_mask(T, S, pos) if mask is None else mask
            swa_mask = sliding_window_mask(T, S, pos, swa)
            if mask is not None:
                swa_mask = swa_mask & mask  # caller's mask composes with SWA
        else:
            # KV axis holds this rank's shard: masks from absolute positions
            full_mask = sp_causal_mask(T, S, pos, sp_axis)
            swa_mask = sp_sliding_window_mask(T, S, pos, swa, sp_axis)
            if mask is not None:
                full_mask = full_mask & mask
                swa_mask = swa_mask & mask
        kinds = layer_kinds if layer_kinds is not None else self.layer_kinds

        def body(carry, per_layer):
            xc = carry
            p, kvs, kind = per_layer
            m = jnp.where(kind == 1, swa_mask, full_mask)
            xc, kvs = self._attention(
                p, xc, kvs, pos, m, tp_axis, kv_commit, sp_axis=sp_axis
            )
            xc = self._moe(p, xc, tp_axis)
            return xc, kvs

        x, kv_out = lax.scan(body, x, (window_params, kv, kinds))
        return x, kv_out

    def normalize(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        return rms_norm(x, edge_params["final_norm"]["weight"], self.config.rms_norm_eps)

    def lm_project(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        if self.config.tie_word_embeddings:
            return x @ edge_params["embed"]["weight"].T
        return x @ edge_params["lm_head"]["weight"]

    # ---- weight mapping ----------------------------------------------
    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        def t(name):
            return np.ascontiguousarray(raw[name].T)

        return {
            "attn_norm": raw["input_layernorm.weight"],
            "wq": t("self_attn.q_proj.weight"),
            "bq": raw["self_attn.q_proj.bias"],
            "wk": t("self_attn.k_proj.weight"),
            "bk": raw["self_attn.k_proj.bias"],
            "wv": t("self_attn.v_proj.weight"),
            "bv": raw["self_attn.v_proj.bias"],
            "wo": t("self_attn.o_proj.weight"),
            "bo": raw["self_attn.o_proj.bias"],
            "sinks": raw["self_attn.sinks"],
            "mlp_norm": raw["post_attention_layernorm.weight"],
            "router_w": t("mlp.router.weight"),
            "router_b": raw["mlp.router.bias"],
            # experts are stored [E, D, 2F]/[E, F, D]: already (in,out)-oriented
            "gate_up": raw["mlp.experts.gate_up_proj"],
            "gate_up_b": raw["mlp.experts.gate_up_proj_bias"],
            "down": raw["mlp.experts.down_proj"],
            "down_b": raw["mlp.experts.down_proj_bias"],
        }

