"""GPT-OSS-family ring model: MoE + alternating sliding/full attention + sinks.

Reference analog: src/dnet/core/models/gpt_oss.py (dual full/SWA masks,
GLOBAL-vs-LOCAL cache handling, MXFP4 desharding).  TPU-first design:

- Alternating layer kinds stay inside ONE `lax.scan`: both masks are built
  once per window and each layer selects by its kind scalar (kind rides the
  scan xs, so one compiled program serves both kinds).  KV is full-length
  with an SWA mask — trades the RotatingKVCache's memory saving for a single
  fused program; grouped scans can reclaim the memory later.
- MoE routes through ops/moe.py: dense masked einsum at decode size,
  capacity dispatch at prefill size, and all_to_all expert parallelism when
  a tp axis is present (`tp_axis` shards the EXPERT dim, so tensor-parallel
  ranks are expert-parallel here; the dense/dispatch paths psum partial
  outputs, the a2a path routes per-expert token buffers over ICI).
- Attention sinks ride through ops.attention.attend(sinks=...).

Weights follow the HF dequantized layout (experts as [E, D, 2F]/[E, F, D]
with interleaved gate/up columns, clamped swiglu alpha=1.702, limit=7).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnet_tpu.parallel.tp_collectives import tp_all_reduce

from dnet_tpu.models.base import ModelConfig, RingModel
from dnet_tpu.ops.attention import (
    cached_attend,
    causal_mask,
    rotating_cached_attend,
    sliding_window_mask,
    sp_causal_mask,
    sp_sliding_window_mask,
)
from dnet_tpu.ops.norms import rms_norm
from dnet_tpu.ops.quant import dq, lead_dim, out_dim
from dnet_tpu.ops.rope import apply_rope, rope_frequencies

ALPHA = 1.702
LIMIT = 7.0


class GptOssRingModel(RingModel):
    model_type = "gpt_oss"

    def __init__(self, config: ModelConfig, layers):
        super().__init__(config, layers)
        inv_freq, self.rope_scale = rope_frequencies(
            config.head_dim,
            config.rope_theta,
            config.rope_scaling,
            config.max_position_embeddings,
        )
        self.inv_freq = jnp.asarray(inv_freq)
        kinds = config.layer_types or ["full_attention"] * config.num_hidden_layers
        # kind per ASSIGNED layer (0=full, 1=sliding), aligned with the stack
        kind_list = [1 if kinds[a] == "sliding_attention" else 0 for a in self.layers]
        self.layer_kinds = jnp.asarray(kind_list, dtype=jnp.int32)
        # paired layout: gpt-oss alternates sliding/full, so stacking the
        # even and odd halves separately makes each half kind-homogeneous —
        # static masks, and the sliding half's cache can be an O(window)
        # ring buffer instead of full length
        self.pair_kinds = None
        if len(kind_list) >= 2 and len(kind_list) % 2 == 0:
            a, b = kind_list[0::2], kind_list[1::2]
            if len(set(a)) == 1 and len(set(b)) == 1:
                self.pair_kinds = (a[0], b[0])

    # ---- pure compute -------------------------------------------------
    def _attention(self, p, x, kvs, pos, mask, tp_axis, kv_commit, sp_axis=None,
                   rotating_window: int = 0, t_real=None, causal: bool = False):
        cfg = self.config
        B, T, D = x.shape
        Hd = cfg.head_dim
        H = out_dim(p["wq"]) // Hd
        KVH = out_dim(p["wk"]) // Hd

        h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
        q = (h @ dq(p["wq"]) + p["bq"]).reshape(B, T, H, Hd)
        k = (h @ dq(p["wk"]) + p["bk"]).reshape(B, T, KVH, Hd)
        v = (h @ dq(p["wv"]) + p["bv"]).reshape(B, T, KVH, Hd)
        positions = pos + jnp.arange(T)
        q = apply_rope(q, positions, self.inv_freq, self.rope_scale)
        k = apply_rope(k, positions, self.inv_freq, self.rope_scale)
        if rotating_window:
            attn, kvs = rotating_cached_attend(
                q, k, v, kvs, pos, rotating_window,
                kv_commit=kv_commit, sinks=p["sinks"], t_real=t_real,
            )
        else:
            attn, kvs = cached_attend(
                q, k, v, kvs, pos, mask,
                kv_commit=kv_commit, sp_axis=sp_axis, sinks=p["sinks"],
                causal=causal,
            )
        out = attn.reshape(B, T, H * Hd) @ dq(p["wo"])
        if tp_axis is not None:
            # out-proj all-reduce through the quantizable TP seam
            out = tp_all_reduce(out, tp_axis)
        out = out + p["bo"]  # bias replicated: add once, after the psum
        return x + out, kvs

    def _moe(self, p, x, tp_axis):
        from dnet_tpu.ops.moe import moe_apply

        B, T, D = x.shape
        h = rms_norm(x, p["mlp_norm"], self.config.rms_norm_eps)
        flat = h.reshape(B * T, D)
        N = flat.shape[0]
        k = self.config.num_experts_per_tok
        E_local = lead_dim(p["gate_up"])

        # router over the FULL expert set (router weights replicated)
        logits = flat @ p["router_w"] + p["router_b"]  # [N, E_total]
        top_vals, top_idx = lax.top_k(logits, k)
        top_probs = jax.nn.softmax(top_vals.astype(jnp.float32), axis=-1).astype(flat.dtype)

        def ffn(xe):  # per-expert buffers [E*, C*, D] -> [E*, C*, D]
            gu = jnp.einsum("ecd,edf->ecf", xe, dq(p["gate_up"])) + p["gate_up_b"][:, None, :]
            gate = jnp.clip(gu[..., ::2], max=LIMIT)
            up = jnp.clip(gu[..., 1::2], min=-LIMIT, max=LIMIT)
            glu = gate * jax.nn.sigmoid(gate * ALPHA)
            return (
                jnp.einsum("ecf,efd->ecd", (up + 1.0) * glu, dq(p["down"]))
                + p["down_b"][:, None, :]
            )

        def dense():  # every token x every local expert, scores mask the sum
            scores = jnp.zeros_like(logits).at[
                jnp.arange(N)[:, None], top_idx
            ].set(top_probs)
            gate_up = jnp.einsum("nd,edf->nef", flat, dq(p["gate_up"])) + p["gate_up_b"]
            gate = jnp.clip(gate_up[..., ::2], max=LIMIT)
            up = jnp.clip(gate_up[..., 1::2], min=-LIMIT, max=LIMIT)
            glu = gate * jax.nn.sigmoid(gate * ALPHA)
            inner = (up + 1.0) * glu  # [N, E_local, F]
            expert_out = jnp.einsum("nef,efd->ned", inner, dq(p["down"])) + p["down_b"]
            if tp_axis is not None:
                e_off = lax.axis_index(tp_axis) * E_local
                local_scores = lax.dynamic_slice_in_dim(scores, e_off, E_local, axis=1)
            else:
                local_scores = scores
            return jnp.einsum("ned,ne->nd", expert_out, local_scores)

        out, partial = moe_apply(
            self.moe_impl, flat, top_idx, top_probs, ffn, E_local,
            self.moe_capacity_factor, k, tp_axis, dense,
        )
        if partial:
            # expert-combine all-reduce through the quantizable TP seam
            out = tp_all_reduce(out, tp_axis)
        return x + out.reshape(B, T, D)

    def _kind_mask(self, kind: int, T: int, S: int, pos, sp_axis, mask):
        """Static-kind mask for one paired half."""
        swa = self.config.sliding_window or (
            S * (1 if sp_axis is None else lax.psum(1, sp_axis))
        )
        if sp_axis is None:
            m = sliding_window_mask(T, S, pos, swa) if kind == 1 else causal_mask(T, S, pos)
        else:
            m = (
                sp_sliding_window_mask(T, S, pos, swa, sp_axis)
                if kind == 1
                else sp_causal_mask(T, S, pos, sp_axis)
            )
        if mask is not None:
            m = m & mask
        return m

    def _apply_paired(
        self, window_params, x, kv, pos, mask, tp_axis, kv_commit, sp_axis,
        t_real=None,
    ):
        """One scan over (even, odd) layer pairs: each half is
        kind-homogeneous, so masks are static and a sliding half whose cache
        is shorter than the full half's runs as an O(window) ring buffer."""
        T = x.shape[1]
        halves = [h for h in ("a", "b") if h in window_params]
        W_cfg = self.config.sliding_window
        ctx = {}
        for i, h in enumerate(halves):
            kind = self.pair_kinds[i]
            S_h = kv[h]["k"].shape[2]
            # a W-row cache marks the ring-buffer layout (init_kv sizes a
            # sliding half to W only when rotating) — compare against the
            # configured window, NOT the other half, or a both-halves-
            # sliding window would silently fall into the clamped-write path
            rotating = kind == 1 and 0 < W_cfg == S_h and sp_axis is None
            # a full-attention half with no extra caller mask is the plain
            # causal predicate: declare it (flash path) instead of
            # materializing the mask
            causal = kind == 0 and sp_axis is None and mask is None
            m = (
                None
                if rotating or causal
                else self._kind_mask(kind, T, S_h, pos, sp_axis, mask)
            )
            W = self.config.sliding_window if rotating else 0
            ctx[h] = (m, W, causal)

        def body(carry, per):
            xc = carry
            kv_out = {}
            for i, h in enumerate(halves):
                p, kvs = per[h]
                m, W, causal = ctx[h]
                xc, kvs = self._attention(
                    p, xc, kvs, pos, m, tp_axis, kv_commit,
                    sp_axis=sp_axis, rotating_window=W, t_real=t_real,
                    causal=causal,
                )
                xc = self._moe(p, xc, tp_axis)
                kv_out[h] = kvs
            return xc, kv_out

        xs = {h: (window_params[h], kv[h]) for h in halves}
        x, kv_out = lax.scan(body, x, xs)
        return x, kv_out

    def apply_window(
        self,
        window_params: dict,
        x: jnp.ndarray,
        kv: dict,
        pos: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        layer_kinds: Optional[jnp.ndarray] = None,
        tp_axis: Optional[str] = None,
        kv_commit=None,
        sp_axis: Optional[str] = None,
        t_real=None,
    ) -> Tuple[jnp.ndarray, dict]:
        if "a" in window_params:  # paired layout (fit/mesh engines)
            return self._apply_paired(
                window_params, x, kv, pos, mask, tp_axis, kv_commit, sp_axis,
                t_real=t_real,
            )
        T, S = x.shape[1], kv["k"].shape[2]
        swa = self.config.sliding_window or (
            S * (1 if sp_axis is None else lax.psum(1, sp_axis))
        )
        if sp_axis is None:
            full_mask = causal_mask(T, S, pos) if mask is None else mask
            swa_mask = sliding_window_mask(T, S, pos, swa)
            if mask is not None:
                swa_mask = swa_mask & mask  # caller's mask composes with SWA
        else:
            # KV axis holds this rank's shard: masks from absolute positions
            full_mask = sp_causal_mask(T, S, pos, sp_axis)
            swa_mask = sp_sliding_window_mask(T, S, pos, swa, sp_axis)
            if mask is not None:
                full_mask = full_mask & mask
                swa_mask = swa_mask & mask
        kinds = layer_kinds if layer_kinds is not None else self.layer_kinds

        def body(carry, per_layer):
            xc = carry
            p, kvs, kind = per_layer
            m = jnp.where(kind == 1, swa_mask, full_mask)
            xc, kvs = self._attention(
                p, xc, kvs, pos, m, tp_axis, kv_commit, sp_axis=sp_axis
            )
            xc = self._moe(p, xc, tp_axis)
            return xc, kvs

        x, kv_out = lax.scan(body, x, (window_params, kv, kinds))
        return x, kv_out

    def normalize(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        return rms_norm(x, edge_params["final_norm"]["weight"], self.config.rms_norm_eps)

    # ---- layout -------------------------------------------------------
    def stack_layers(self, per_layer):
        if self.pair_kinds is None:
            return super().stack_layers(per_layer)
        return {
            "a": RingModel.stack_layers(per_layer[0::2]),
            "b": RingModel.stack_layers(per_layer[1::2]),
        }

    def quantize_params(self, stacked, bits: int, scale_dtype=None, group_size: int = 0):
        from dnet_tpu.ops.quant import quantize_tree

        if "a" not in stacked:
            return super().quantize_params(stacked, bits, scale_dtype, group_size)
        return {
            h: quantize_tree(
                tree, self.quant_keys, bits=bits, scale_dtype=scale_dtype,
                group_size=group_size,
            )
            for h, tree in stacked.items()
        }

    def init_kv(self, n_layers, batch, max_seq, dtype="bfloat16", quant_bits=0,
                rotating=True):
        from dnet_tpu.core.kvcache import init_cache

        if self.pair_kinds is None or n_layers != len(self.layers):
            return super().init_kv(
                n_layers, batch, max_seq, dtype, quant_bits, rotating
            )
        W = self.config.sliding_window

        def cache(kind):
            s = max_seq
            if rotating and kind == 1 and 0 < W < max_seq:
                s = W
            cfg = self.kv_config(
                n_layers // 2, batch, s, dtype, quant_bits=quant_bits
            )
            return init_cache(cfg)

        return {"a": cache(self.pair_kinds[0]), "b": cache(self.pair_kinds[1])}

    def kv_rewindable(self, max_seq: int) -> bool:
        """False when init_kv would allocate rotating ring-buffer SWA caches
        (paired layout + a sliding half shorter than max_seq): wrap-around
        writes evict live rows, so a speculative rewind would corrupt the
        attended window."""
        W = self.config.sliding_window
        if self.pair_kinds is None or not (0 < W < max_seq):
            return True
        return 1 not in tuple(int(k) for k in self.pair_kinds)

    # ---- weight mapping ----------------------------------------------
    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        def t(name):
            return np.ascontiguousarray(raw[name].T)

        return {
            "attn_norm": raw["input_layernorm.weight"],
            "wq": t("self_attn.q_proj.weight"),
            "bq": raw["self_attn.q_proj.bias"],
            "wk": t("self_attn.k_proj.weight"),
            "bk": raw["self_attn.k_proj.bias"],
            "wv": t("self_attn.v_proj.weight"),
            "bv": raw["self_attn.v_proj.bias"],
            "wo": t("self_attn.o_proj.weight"),
            "bo": raw["self_attn.o_proj.bias"],
            "sinks": raw["self_attn.sinks"],
            "mlp_norm": raw["post_attention_layernorm.weight"],
            "router_w": t("mlp.router.weight"),
            "router_b": raw["mlp.router.bias"],
            # experts are stored [E, D, 2F]/[E, F, D]: already (in,out)-oriented
            "gate_up": raw["mlp.experts.gate_up_proj"],
            "gate_up_b": raw["mlp.experts.gate_up_proj_bias"],
            "down": raw["mlp.experts.down_proj"],
            "down_b": raw["mlp.experts.down_proj_bias"],
        }

