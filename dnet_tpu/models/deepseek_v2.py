"""DeepSeek-V2-family ring model: MLA attention + shared/routed MoE.

Reference analog: src/dnet/core/models/deepseek_v2.py (MLA-style model,
asymmetric head dims).  Architecture (matching transformers' DeepseekV2*):

- MLA: queries via optional LoRA (q_a -> norm -> q_b), KV via a compressed
  latent (kv_a -> norm -> kv_b) plus a SHARED per-token rope key (MQA-style);
  rope uses the interleaved/complex-pair convention; K caches nope+rope
  (qk_head_dim) while V caches v_head_dim — the KV cache is asymmetric.
- Layers < first_k_dense_replace use a dense swiglu MLP; the rest use MoE:
  softmax-then-topk routing (greedy or group-limited), routed_scaling_factor,
  plus always-on shared experts.
- Dense vs MoE layers have different param structures, so the window is TWO
  stacked segments ({"dense": ..., "moe": ...}), each applied with one
  lax.scan — compile time is layer-count-independent (two programs), and a
  contiguous layer range is always a dense prefix + moe suffix.  MoE expert
  compute is dense-weighted (exact numerics); `tp_axis` shards attention
  heads and the EXPERT dim (expert-parallel ranks) with psum seams.
- For the mesh ring (pp sharding), segments are zero-padded to pp
  divisibility (zero o/down projections make a padded layer an exact
  residual no-op) and the ring runs TWO laps (`ring_phases = 2`): every
  rank applies its dense slice on lap 0 and its moe slice on lap 1, so the
  global execution order stays all-dense-then-all-moe.  The KV cache is laid
  out per-rank (dense rows then moe rows), which is exactly the local
  slicing apply_window already uses.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnet_tpu.core.kvcache import KVConfig
from dnet_tpu.models.base import ModelConfig, RingModel
from dnet_tpu.models.segments import TwoSegmentStackMixin
from dnet_tpu.parallel.tp_collectives import tp_all_reduce
from dnet_tpu.ops.attention import cached_attend
from dnet_tpu.ops.norms import rms_norm
from dnet_tpu.ops.quant import dq
from dnet_tpu.ops.rope import apply_rope_interleaved, rope_frequencies


class DeepseekV2RingModel(TwoSegmentStackMixin, RingModel):
    model_type = "deepseek_v2"
    supports_kv_commit = True
    ring_phases = 2  # mesh ring: lap 0 = dense slices, lap 1 = moe slices
    quant_keys = frozenset(
        {"wq", "wq_a", "wq_b", "wkv_a", "wkv_b", "wo",  # MLA projections
         "w_gate", "w_up", "w_down",  # dense mlp
         "e_gate", "e_up", "e_down", "s_gate", "s_up", "s_down"}  # MoE
    )  # router gate_w stays f32 (routing decisions are precision-sensitive)

    def __init__(self, config: ModelConfig, layers):
        super().__init__(config, layers)
        x = config.extra
        self.q_lora_rank = x.get("q_lora_rank")
        self.qk_nope_head_dim = x.get("qk_nope_head_dim", 128)
        self.qk_rope_head_dim = x.get("qk_rope_head_dim", 64)
        self.kv_lora_rank = x.get("kv_lora_rank", 512)
        self.v_head_dim = x.get("v_head_dim", 128)
        self.qk_head_dim = self.qk_nope_head_dim + self.qk_rope_head_dim
        self.n_routed_experts = x.get("n_routed_experts", 0)
        self.n_shared_experts = x.get("n_shared_experts", 0)
        self.moe_intermediate_size = x.get("moe_intermediate_size", 0)
        self.first_k_dense_replace = x.get("first_k_dense_replace", 0)
        self.routed_scaling_factor = x.get("routed_scaling_factor", 1.0)
        self.topk_method = x.get("topk_method", "greedy")
        self.n_group = x.get("n_group", 1)
        self.topk_group = x.get("topk_group", 1)
        self.norm_topk_prob = x.get("norm_topk_prob", False)
        self.num_experts_per_tok = x.get("num_experts_per_tok", 0)

        inv_freq, self.rope_scale = rope_frequencies(
            self.qk_rope_head_dim,
            config.rope_theta,
            config.rope_scaling,
            config.max_position_embeddings,
        )
        self.inv_freq = jnp.asarray(inv_freq)

        # Original DeepSeek-V2 YaRN: softmax scale is compensated by
        # mscale(factor, mscale_all_dim)^2 (the model was TRAINED with this;
        # the transformers port drops it when mscale == mscale_all_dim, which
        # shrinks logits ~1.6x on real checkpoints).
        self.softmax_scale = self.qk_head_dim**-0.5
        rs = config.rope_scaling or {}
        if rs.get("rope_type", rs.get("type")) == "yarn":
            factor = rs.get("factor", 1.0)
            msc_all = rs.get("mscale_all_dim", 0)
            if msc_all and factor > 1:
                import math

                mscale = 0.1 * msc_all * math.log(factor) + 1.0
                self.softmax_scale = self.softmax_scale * mscale * mscale

    def is_moe_layer(self, abs_layer: int) -> bool:
        return self.n_routed_experts > 0 and abs_layer >= self.first_k_dense_replace

    # ---- cache: asymmetric dims --------------------------------------
    def kv_config(self, n_layers, batch, max_seq, dtype="bfloat16", quant_bits=0) -> KVConfig:
        return KVConfig(
            n_layers=n_layers,
            batch=batch,
            max_seq=max_seq,
            n_kv_heads=self.config.num_attention_heads,
            head_dim=self.qk_head_dim,
            dtype=dtype,
            v_head_dim=self.v_head_dim,
            quant_bits=quant_bits,
        )

    # ---- pure compute -------------------------------------------------
    def _attention(
        self, p, x, kvs, pos, mask, tp_axis=None, kv_commit=None, sp_axis=None
    ):
        cfg = self.config
        B, T, D = x.shape
        nope, rope_d, vd = self.qk_nope_head_dim, self.qk_rope_head_dim, self.v_head_dim

        h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
        if self.q_lora_rank is None:
            q = h @ dq(p["wq"])
        else:
            qa = rms_norm(h @ dq(p["wq_a"]), p["q_a_norm"], 1e-6)
            q = qa @ dq(p["wq_b"])
        # local head count from the (possibly tp-sharded) projection shape
        H = q.shape[-1] // self.qk_head_dim
        q = q.reshape(B, T, H, self.qk_head_dim)
        q_nope, q_pe = q[..., :nope], q[..., nope:]

        ckv = h @ dq(p["wkv_a"])  # [B, T, kv_lora + rope_d] (replicated)
        k_latent, k_pe = ckv[..., : self.kv_lora_rank], ckv[..., self.kv_lora_rank:]
        k_latent = rms_norm(k_latent, p["kv_a_norm"], 1e-6)
        kv = (k_latent @ dq(p["wkv_b"])).reshape(B, T, H, nope + vd)
        k_nope, v = kv[..., :nope], kv[..., nope:]

        positions = pos + jnp.arange(T)
        q_pe = apply_rope_interleaved(q_pe, positions, self.inv_freq, self.rope_scale)
        k_pe = apply_rope_interleaved(
            k_pe[:, :, None, :], positions, self.inv_freq, self.rope_scale
        )  # [B, T, 1, rope_d] — shared across heads (MQA-style)
        k_pe = jnp.broadcast_to(k_pe, (B, T, H, rope_d))

        q_full = jnp.concatenate([q_nope, q_pe], axis=-1)
        k_full = jnp.concatenate([k_nope, k_pe], axis=-1)

        # shared body incl. the sp path: with sp_axis the cache holds this
        # rank's sequence shard and attention runs as distributed
        # flash-decoding with an LSE combine (ops/ring_attention.py) —
        # MLA's asymmetric K/V head dims flow through unchanged.  mask=None
        # non-sp declares the plain causal predicate: prefill takes the
        # Pallas flash kernel on TPU (ops/flash_attention.py)
        attn, kvs = cached_attend(
            q_full, k_full, v, kvs, pos, mask,
            kv_commit=kv_commit, sp_axis=sp_axis, scale=self.softmax_scale,
            causal=mask is None,
        )
        out = attn.reshape(B, T, H * vd) @ dq(p["wo"])
        if tp_axis is not None:
            # out-proj all-reduce through the quantizable TP seam
            out = tp_all_reduce(out, tp_axis)
        return x + out, kvs

    def _dense_mlp(self, p_prefix: dict, h: jnp.ndarray) -> jnp.ndarray:
        gate = h @ dq(p_prefix["w_gate"])
        up = h @ dq(p_prefix["w_up"])
        return (jax.nn.silu(gate) * up) @ dq(p_prefix["w_down"])

    def _moe(self, p, x, tp_axis=None):
        B, T, D = x.shape
        h = rms_norm(x, p["mlp_norm"], self.config.rms_norm_eps)
        flat = h.reshape(B * T, D)

        logits = flat.astype(jnp.float32) @ p["gate_w"].astype(jnp.float32)
        scores = jax.nn.softmax(logits, axis=-1)  # [N, E] f32 softmax over ALL
        k = self.num_experts_per_tok
        if self.topk_method == "group_limited_greedy":
            N, E = scores.shape
            g = self.n_group
            group_scores = scores.reshape(N, g, E // g).max(axis=-1)
            _, group_idx = lax.top_k(group_scores, self.topk_group)
            group_mask = jnp.zeros_like(group_scores).at[
                jnp.arange(N)[:, None], group_idx
            ].set(1.0)
            score_mask = jnp.repeat(group_mask, E // g, axis=1)
            masked = jnp.where(score_mask > 0, scores, 0.0)
            topk_w, topk_idx = lax.top_k(masked, k)
        else:  # greedy (DeepSeek-V2-Lite)
            topk_w, topk_idx = lax.top_k(scores, k)
        if self.norm_topk_prob:
            topk_w = topk_w / jnp.sum(topk_w, axis=-1, keepdims=True)
        topk_w = topk_w * self.routed_scaling_factor

        from dnet_tpu.ops.moe import moe_apply, swiglu_expert_closures

        topk_idx = topk_idx.astype(jnp.int32)
        effn, dense, E_local = swiglu_expert_closures(
            p, flat, scores, topk_idx, topk_w, tp_axis
        )
        routed, routed_partial = moe_apply(
            self.moe_impl, flat, topk_idx, topk_w, effn, E_local,
            self.moe_capacity_factor, k, tp_axis, dense,
        )

        # shared experts are Megatron-split over tp (col/row), so their
        # partial output always reduces over tp; the routed partial joins
        # that psum except on the a2a path, which returns a full output
        shared = self._dense_mlp(
            {"w_gate": p["s_gate"], "w_up": p["s_up"], "w_down": p["s_down"]}, flat
        )
        if tp_axis is not None:
            if routed_partial:
                out = tp_all_reduce(routed.astype(flat.dtype) + shared, tp_axis)
            else:
                out = routed.astype(flat.dtype) + tp_all_reduce(shared, tp_axis)
        else:
            out = routed.astype(flat.dtype) + shared
        return x + out.reshape(B, T, D)

    def _layer(
        self, p: dict, x, kvs, pos, mask, tp_axis=None, kv_commit=None,
        sp_axis=None,
    ):
        x, kvs = self._attention(p, x, kvs, pos, mask, tp_axis, kv_commit, sp_axis)
        if "e_gate" in p:
            x = self._moe(p, x, tp_axis)
        else:
            h = rms_norm(x, p["mlp_norm"], self.config.rms_norm_eps)
            out = self._dense_mlp(p, h)
            if tp_axis is not None:
                # down-proj all-reduce through the quantizable TP seam
                out = tp_all_reduce(out, tp_axis)
            x = x + out
        return x, kvs

    def apply_window(
        self,
        window_params,
        x: jnp.ndarray,
        kv: dict,
        pos: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        layer_kinds: Optional[jnp.ndarray] = None,
        tp_axis: Optional[str] = None,
        kv_commit=None,
        sp_axis: Optional[str] = None,
        phase=None,
        t_real=None,  # full-length caches overwrite padding before reading
    ) -> Tuple[jnp.ndarray, dict]:
        """Two-segment scan: the window's dense prefix, then its moe suffix.

        `phase` (traced int, mesh ring only) selects ONE segment per call:
        the ring runs `ring_phases` laps so the global layer order stays
        all-dense-then-all-moe even though each pp rank holds a slice of
        both segments.  The segment machinery itself is shared with mixed
        qwen3_moe (models/segments.py).
        """
        # the causal predicate stays implicit (mask=None) under sp too:
        # cached_attend owns the rank-local sp mask (or the TPU split-K
        # flash-decode partials, which honor self.softmax_scale)
        return self._apply_segments(
            window_params, x, kv, pos, mask, tp_axis, kv_commit, sp_axis, phase
        )

    def normalize(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        return rms_norm(x, edge_params["final_norm"]["weight"], self.config.rms_norm_eps)

    # ---- weight mapping ----------------------------------------------
    def stack_layers(self, per_layer: List[Dict[str, np.ndarray]]):
        """Two homogeneous stacked segments: the window's dense prefix and
        its moe suffix (a contiguous layer range is always dense-then-moe
        because dense layers come first globally)."""
        n_dense = sum(1 for a in self.layers if not self.is_moe_layer(a))
        out: Dict[str, Any] = {}
        if per_layer[:n_dense]:
            out["dense"] = RingModel.stack_layers(per_layer[:n_dense])
        if per_layer[n_dense:]:
            out["moe"] = RingModel.stack_layers(per_layer[n_dense:])
        return out

    # quantize_params / wrap_offload_layer / pad_mesh_segments come from
    # TwoSegmentStackMixin (shared with mixed qwen3_moe)

    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        def t(name):
            return np.ascontiguousarray(raw[name].T)

        p: Dict[str, np.ndarray] = {
            "attn_norm": raw["input_layernorm.weight"],
            "mlp_norm": raw["post_attention_layernorm.weight"],
            "wkv_a": t("self_attn.kv_a_proj_with_mqa.weight"),
            "kv_a_norm": raw["self_attn.kv_a_layernorm.weight"],
            "wkv_b": t("self_attn.kv_b_proj.weight"),
            "wo": t("self_attn.o_proj.weight"),
        }
        if "self_attn.q_proj.weight" in raw:
            p["wq"] = t("self_attn.q_proj.weight")
        else:
            p["wq_a"] = t("self_attn.q_a_proj.weight")
            p["q_a_norm"] = raw["self_attn.q_a_layernorm.weight"]
            p["wq_b"] = t("self_attn.q_b_proj.weight")

        if "mlp.gate.weight" in raw:  # MoE layer
            p["gate_w"] = t("mlp.gate.weight")
            e_gate, e_up, e_down = [], [], []
            e = 0
            while f"mlp.experts.{e}.gate_proj.weight" in raw:
                e_gate.append(t(f"mlp.experts.{e}.gate_proj.weight"))
                e_up.append(t(f"mlp.experts.{e}.up_proj.weight"))
                e_down.append(t(f"mlp.experts.{e}.down_proj.weight"))
                e += 1
            p["e_gate"] = np.stack(e_gate)
            p["e_up"] = np.stack(e_up)
            p["e_down"] = np.stack(e_down)
            p["s_gate"] = t("mlp.shared_experts.gate_proj.weight")
            p["s_up"] = t("mlp.shared_experts.up_proj.weight")
            p["s_down"] = t("mlp.shared_experts.down_proj.weight")
        else:  # dense layer
            p["w_gate"] = t("mlp.gate_proj.weight")
            p["w_up"] = t("mlp.up_proj.weight")
            p["w_down"] = t("mlp.down_proj.weight")
        return p

