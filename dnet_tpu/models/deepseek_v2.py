"""DeepSeek-V2-family ring model: MLA attention + shared/routed MoE.

Reference analog: src/dnet/core/models/deepseek_v2.py (MLA-style model,
asymmetric head dims).  Architecture (matching transformers' DeepseekV2*):

- MLA: queries via optional LoRA (q_a -> norm -> q_b), KV via a compressed
  latent (kv_a -> norm -> kv_b) plus a SHARED per-token rope key (MQA-style);
  rope uses the interleaved/complex-pair convention; K caches nope+rope
  (qk_head_dim) while V caches v_head_dim — the KV cache is asymmetric.
- Layers < first_k_dense_replace use a dense swiglu MLP; the rest use MoE:
  softmax-then-topk routing (greedy or group-limited), routed_scaling_factor,
  plus always-on shared experts.
- Dense vs MoE layers have different param structures, so the stacked window
  is a LIST of per-layer dicts (python-unrolled inside jit) instead of a
  lax.scan — correctness first; two-segment scans are the planned
  optimization.  MoE expert compute is dense-weighted (exact numerics).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnet_tpu.core.kvcache import KVConfig, read_kv, write_kv
from dnet_tpu.models.base import ModelConfig, RingModel
from dnet_tpu.ops.attention import attend, causal_mask
from dnet_tpu.ops.norms import rms_norm
from dnet_tpu.ops.quant import dq
from dnet_tpu.ops.rope import apply_rope_interleaved, rope_frequencies


class DeepseekV2RingModel(RingModel):
    model_type = "deepseek_v2"
    supports_kv_commit = False  # apply_window rejects kv_commit (pp-only)
    quant_keys = frozenset(
        {"wq", "wq_a", "wq_b", "wkv_a", "wkv_b", "wo",  # MLA projections
         "w_gate", "w_up", "w_down",  # dense mlp
         "e_gate", "e_up", "e_down", "s_gate", "s_up", "s_down"}  # MoE
    )  # router gate_w stays f32 (routing decisions are precision-sensitive)

    def __init__(self, config: ModelConfig, layers):
        super().__init__(config, layers)
        x = config.extra
        self.q_lora_rank = x.get("q_lora_rank")
        self.qk_nope_head_dim = x.get("qk_nope_head_dim", 128)
        self.qk_rope_head_dim = x.get("qk_rope_head_dim", 64)
        self.kv_lora_rank = x.get("kv_lora_rank", 512)
        self.v_head_dim = x.get("v_head_dim", 128)
        self.qk_head_dim = self.qk_nope_head_dim + self.qk_rope_head_dim
        self.n_routed_experts = x.get("n_routed_experts", 0)
        self.n_shared_experts = x.get("n_shared_experts", 0)
        self.moe_intermediate_size = x.get("moe_intermediate_size", 0)
        self.first_k_dense_replace = x.get("first_k_dense_replace", 0)
        self.routed_scaling_factor = x.get("routed_scaling_factor", 1.0)
        self.topk_method = x.get("topk_method", "greedy")
        self.n_group = x.get("n_group", 1)
        self.topk_group = x.get("topk_group", 1)
        self.norm_topk_prob = x.get("norm_topk_prob", False)
        self.num_experts_per_tok = x.get("num_experts_per_tok", 0)

        inv_freq, self.rope_scale = rope_frequencies(
            self.qk_rope_head_dim,
            config.rope_theta,
            config.rope_scaling,
            config.max_position_embeddings,
        )
        self.inv_freq = jnp.asarray(inv_freq)

        # Original DeepSeek-V2 YaRN: softmax scale is compensated by
        # mscale(factor, mscale_all_dim)^2 (the model was TRAINED with this;
        # the transformers port drops it when mscale == mscale_all_dim, which
        # shrinks logits ~1.6x on real checkpoints).
        self.softmax_scale = self.qk_head_dim**-0.5
        rs = config.rope_scaling or {}
        if rs.get("rope_type", rs.get("type")) == "yarn":
            factor = rs.get("factor", 1.0)
            msc_all = rs.get("mscale_all_dim", 0)
            if msc_all and factor > 1:
                import math

                mscale = 0.1 * msc_all * math.log(factor) + 1.0
                self.softmax_scale = self.softmax_scale * mscale * mscale

    def is_moe_layer(self, abs_layer: int) -> bool:
        return self.n_routed_experts > 0 and abs_layer >= self.first_k_dense_replace

    # ---- cache: asymmetric dims --------------------------------------
    def kv_config(self, n_layers, batch, max_seq, dtype="bfloat16", quant_bits=0) -> KVConfig:
        return KVConfig(
            n_layers=n_layers,
            batch=batch,
            max_seq=max_seq,
            n_kv_heads=self.config.num_attention_heads,
            head_dim=self.qk_head_dim,
            dtype=dtype,
            v_head_dim=self.v_head_dim,
            quant_bits=quant_bits,
        )

    # ---- pure compute -------------------------------------------------
    def embed(self, edge_params: dict, tokens: jnp.ndarray) -> jnp.ndarray:
        return edge_params["embed"]["weight"][tokens]

    def _attention(self, p, x, kvs, pos, mask):
        cfg = self.config
        B, T, D = x.shape
        H = cfg.num_attention_heads
        nope, rope_d, vd = self.qk_nope_head_dim, self.qk_rope_head_dim, self.v_head_dim

        h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
        if self.q_lora_rank is None:
            q = h @ dq(p["wq"])
        else:
            qa = rms_norm(h @ dq(p["wq_a"]), p["q_a_norm"], 1e-6)
            q = qa @ dq(p["wq_b"])
        q = q.reshape(B, T, H, self.qk_head_dim)
        q_nope, q_pe = q[..., :nope], q[..., nope:]

        ckv = h @ dq(p["wkv_a"])  # [B, T, kv_lora + rope_d]
        k_latent, k_pe = ckv[..., : self.kv_lora_rank], ckv[..., self.kv_lora_rank:]
        k_latent = rms_norm(k_latent, p["kv_a_norm"], 1e-6)
        kv = (k_latent @ dq(p["wkv_b"])).reshape(B, T, H, nope + vd)
        k_nope, v = kv[..., :nope], kv[..., nope:]

        positions = pos + jnp.arange(T)
        q_pe = apply_rope_interleaved(q_pe, positions, self.inv_freq, self.rope_scale)
        k_pe = apply_rope_interleaved(
            k_pe[:, :, None, :], positions, self.inv_freq, self.rope_scale
        )  # [B, T, 1, rope_d] — shared across heads (MQA-style)
        k_pe = jnp.broadcast_to(k_pe, (B, T, H, rope_d))

        q_full = jnp.concatenate([q_nope, q_pe], axis=-1)
        k_full = jnp.concatenate([k_nope, k_pe], axis=-1)

        kvs = write_kv(kvs, k_full, v, pos)
        kc, vc = read_kv(kvs)
        attn = attend(q_full, kc, vc, mask=mask, scale=self.softmax_scale)
        out = attn.reshape(B, T, H * vd) @ dq(p["wo"])
        return x + out, kvs

    def _dense_mlp(self, p_prefix: dict, h: jnp.ndarray) -> jnp.ndarray:
        gate = h @ dq(p_prefix["w_gate"])
        up = h @ dq(p_prefix["w_up"])
        return (jax.nn.silu(gate) * up) @ dq(p_prefix["w_down"])

    def _moe(self, p, x):
        B, T, D = x.shape
        h = rms_norm(x, p["mlp_norm"], self.config.rms_norm_eps)
        flat = h.reshape(B * T, D)

        logits = flat.astype(jnp.float32) @ p["gate_w"].astype(jnp.float32)
        scores = jax.nn.softmax(logits, axis=-1)  # [N, E] f32 softmax over ALL
        k = self.num_experts_per_tok
        if self.topk_method == "group_limited_greedy":
            N, E = scores.shape
            g = self.n_group
            group_scores = scores.reshape(N, g, E // g).max(axis=-1)
            _, group_idx = lax.top_k(group_scores, self.topk_group)
            group_mask = jnp.zeros_like(group_scores).at[
                jnp.arange(N)[:, None], group_idx
            ].set(1.0)
            score_mask = jnp.repeat(group_mask, E // g, axis=1)
            masked = jnp.where(score_mask > 0, scores, 0.0)
            topk_w, topk_idx = lax.top_k(masked, k)
        else:  # greedy (DeepSeek-V2-Lite)
            topk_w, topk_idx = lax.top_k(scores, k)
        if self.norm_topk_prob:
            topk_w = topk_w / jnp.sum(topk_w, axis=-1, keepdims=True)
        topk_w = topk_w * self.routed_scaling_factor

        weights = jnp.zeros_like(scores).at[
            jnp.arange(flat.shape[0])[:, None], topk_idx
        ].set(topk_w)  # [N, E]

        # dense-weighted expert compute (exact: zero weight for non-top-k)
        gate = jnp.einsum("nd,edf->nef", flat, dq(p["e_gate"]))
        up = jnp.einsum("nd,edf->nef", flat, dq(p["e_up"]))
        inner = jax.nn.silu(gate) * up
        expert_out = jnp.einsum("nef,efd->ned", inner, dq(p["e_down"]))
        routed = jnp.einsum("ned,ne->nd", expert_out, weights.astype(flat.dtype))

        shared = self._dense_mlp(
            {"w_gate": p["s_gate"], "w_up": p["s_up"], "w_down": p["s_down"]}, flat
        )
        return x + (routed + shared).reshape(B, T, D)

    def _layer(self, p: dict, x, kvs, pos, mask):
        x, kvs = self._attention(p, x, kvs, pos, mask)
        if "e_gate" in p:
            x = self._moe(p, x)
        else:
            h = rms_norm(x, p["mlp_norm"], self.config.rms_norm_eps)
            x = x + self._dense_mlp(p, h)
        return x, kvs

    def apply_window(
        self,
        window_params,
        x: jnp.ndarray,
        kv: dict,
        pos: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        layer_kinds: Optional[jnp.ndarray] = None,
        tp_axis: Optional[str] = None,
        kv_commit=None,
        sp_axis: Optional[str] = None,
    ) -> Tuple[jnp.ndarray, dict]:
        if tp_axis is not None or kv_commit is not None or sp_axis is not None:
            raise NotImplementedError(
                "deepseek_v2 TP/SP/ring-program support is pending; run pp-only"
            )
        if mask is None:
            mask = causal_mask(x.shape[1], kv["k"].shape[2], pos)
        layers: List[dict] = window_params["layers"]
        for li, p in enumerate(layers):
            kvs = jax.tree.map(lambda a: a[li], kv)
            x, kvs = self._layer(p, x, kvs, pos, mask)
            kv = jax.tree.map(lambda full, one: full.at[li].set(one), kv, kvs)
        return x, kv

    def normalize(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        return rms_norm(x, edge_params["final_norm"]["weight"], self.config.rms_norm_eps)

    def lm_project(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        if self.config.tie_word_embeddings:
            return x @ edge_params["embed"]["weight"].T
        return x @ edge_params["lm_head"]["weight"]

    # ---- weight mapping ----------------------------------------------
    def stack_layers(self, per_layer: List[Dict[str, np.ndarray]]):
        """Heterogeneous layers (dense vs MoE): keep a list, no stacking."""
        return {"layers": list(per_layer)}

    def quantize_params(self, stacked, bits: int, scale_dtype=None, group_size: int = 0):
        from dnet_tpu.ops.quant import quantize_tree

        return {
            "layers": [
                quantize_tree(
                    p, self.quant_keys, bits=bits, scale_dtype=scale_dtype,
                    group_size=group_size,
                )
                for p in stacked["layers"]
            ]
        }

    def wrap_offload_layer(self, mapped: Dict[str, np.ndarray]):
        return {"layers": [mapped]}

    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        def t(name):
            return np.ascontiguousarray(raw[name].T)

        p: Dict[str, np.ndarray] = {
            "attn_norm": raw["input_layernorm.weight"],
            "mlp_norm": raw["post_attention_layernorm.weight"],
            "wkv_a": t("self_attn.kv_a_proj_with_mqa.weight"),
            "kv_a_norm": raw["self_attn.kv_a_layernorm.weight"],
            "wkv_b": t("self_attn.kv_b_proj.weight"),
            "wo": t("self_attn.o_proj.weight"),
        }
        if "self_attn.q_proj.weight" in raw:
            p["wq"] = t("self_attn.q_proj.weight")
        else:
            p["wq_a"] = t("self_attn.q_a_proj.weight")
            p["q_a_norm"] = raw["self_attn.q_a_layernorm.weight"]
            p["wq_b"] = t("self_attn.q_b_proj.weight")

        if "mlp.gate.weight" in raw:  # MoE layer
            p["gate_w"] = t("mlp.gate.weight")
            e_gate, e_up, e_down = [], [], []
            e = 0
            while f"mlp.experts.{e}.gate_proj.weight" in raw:
                e_gate.append(t(f"mlp.experts.{e}.gate_proj.weight"))
                e_up.append(t(f"mlp.experts.{e}.up_proj.weight"))
                e_down.append(t(f"mlp.experts.{e}.down_proj.weight"))
                e += 1
            p["e_gate"] = np.stack(e_gate)
            p["e_up"] = np.stack(e_up)
            p["e_down"] = np.stack(e_down)
            p["s_gate"] = t("mlp.shared_experts.gate_proj.weight")
            p["s_up"] = t("mlp.shared_experts.up_proj.weight")
            p["s_down"] = t("mlp.shared_experts.down_proj.weight")
        else:  # dense layer
            p["w_gate"] = t("mlp.gate_proj.weight")
            p["w_up"] = t("mlp.up_proj.weight")
            p["w_down"] = t("mlp.down_proj.weight")
        return p

