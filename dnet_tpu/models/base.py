"""Layer-wise ring model abstraction.

The TPU analog of the reference's `BaseRingModel`
(src/dnet/core/models/base.py:19-109): a shard constructs a model over only
its *assigned* absolute layers and exposes edge ops (embed / normalize /
lm_project) plus windowed layer application.  Unlike the reference's
stateful mlx modules, everything here is functional: parameters are pytrees
of arrays, `apply_window` is a pure function scanned over layer-stacked
params, so it jits/shards/donates cleanly.

Parameter layout:
  params = {
    "embed":      {...}            # only on the shard holding layer 0
    "final_norm": {...}, "lm_head": {...}   # only on the last shard
    "windows":    {window_start: stacked-layer pytree}
  }
Stacked-layer pytrees have a leading layer axis so a window runs as one
`lax.scan` (MXU-friendly, one compiled program regardless of window size).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from dnet_tpu.core.kvcache import KVConfig
from dnet_tpu.ops.quant import QUANTIZABLE


@dataclass
class ModelConfig:
    """Normalized HF config (config.json) subset shared across families."""

    model_type: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_hidden_layers: int
    num_attention_heads: int
    num_key_value_heads: int
    head_dim: int
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rope_scaling: Optional[dict] = None
    tie_word_embeddings: bool = False
    max_position_embeddings: int = 8192
    attention_bias: bool = False
    mlp_bias: bool = False
    sliding_window: int = 0
    layer_types: Optional[List[str]] = None  # e.g. ["sliding_attention", "full_attention", ...]
    # MoE (gpt-oss / mixtral style)
    num_local_experts: int = 0
    num_experts_per_tok: int = 0
    # MLA (deepseek style) and other family-specific extras
    extra: dict = field(default_factory=dict)

    @classmethod
    def from_hf(cls, d: Dict[str, Any]) -> "ModelConfig":
        heads = d["num_attention_heads"]
        head_dim = d.get("head_dim") or d["hidden_size"] // heads
        return cls(
            model_type=d["model_type"],
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d.get("intermediate_size", 4 * d["hidden_size"]),
            num_hidden_layers=d["num_hidden_layers"],
            num_attention_heads=heads,
            num_key_value_heads=d.get("num_key_value_heads", heads),
            head_dim=head_dim,
            rms_norm_eps=d.get("rms_norm_eps", 1e-5),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=d.get("rope_scaling"),
            tie_word_embeddings=d.get("tie_word_embeddings", False),
            max_position_embeddings=d.get("max_position_embeddings", 8192),
            attention_bias=d.get("attention_bias", False),
            mlp_bias=d.get("mlp_bias", False),
            sliding_window=d.get("sliding_window") or 0,
            layer_types=d.get("layer_types"),
            # mixtral/gpt_oss say num_local_experts; qwen3_moe says num_experts
            num_local_experts=d.get("num_local_experts", d.get("num_experts", 0)),
            num_experts_per_tok=d.get("num_experts_per_tok", 0),
            extra=d,
        )


class RingModel(abc.ABC):
    """A shard's view of a model: assigned layers + edge ops.

    Subclasses set `model_type` and implement the pure compute functions and
    the HF-name weight mapping.  Instances hold *no* parameters — params are
    passed to every call (functional style), so the weight-streaming policy
    owns residency.
    """

    model_type: str = ""
    # extension point: a future model whose matmuls can't route through
    # ops.quant.dq sets False and the engine fails fast.  Every current
    # family supports it.
    supports_weight_quant: bool = True
    # apply_window honors the kv_commit gate (required by the pipelined-ring
    # mesh program and continuous batching); deepseek_v2 doesn't yet
    supports_kv_commit: bool = True
    # apply_window accepts an `attend_fn` override replacing the cache
    # write + attention of every layer (ragged paged attention,
    # ops/paged_attention.py).  Only the llama-family stack threads it;
    # models with bespoke attention layouts (gpt_oss paired SWA rings,
    # deepseek MLA) keep the dense-gather decode path.
    supports_paged_attend: bool = False
    # per-layer param names eligible for weight-only quantization (the big
    # matmuls; norms/biases/routers stay float).  Subclasses override.
    quant_keys: frozenset = frozenset(QUANTIZABLE)

    def __init__(self, config: ModelConfig, layers: Sequence[int]):
        self.config = config
        self.layers = sorted(set(int(x) for x in layers))
        self.abs_to_local = {a: i for i, a in enumerate(self.layers)}
        self.is_first = 0 in self.abs_to_local
        self.is_last = (config.num_hidden_layers - 1) in self.abs_to_local
        # per-assigned-layer attention-kind array (models with mixed layer
        # kinds, e.g. gpt_oss SWA/full, set this; None = homogeneous)
        self.layer_kinds = None
        # MoE compute path knobs (ops/moe.py); engines/tests may override
        # the instance attributes after construction
        from dnet_tpu.config import get_settings

        cs = get_settings().compute
        self.moe_impl = cs.moe_impl
        self.moe_capacity_factor = cs.moe_capacity_factor

    # ---- pure compute -------------------------------------------------
    def embed(self, edge_params: dict, tokens: jnp.ndarray) -> jnp.ndarray:
        """tokens [B, T] -> hidden [B, T, D] (maybe-quantized table)."""
        from dnet_tpu.ops.quant import embed_lookup

        return embed_lookup(edge_params["embed"]["weight"], tokens)

    @abc.abstractmethod
    def apply_window(
        self,
        window_params: dict,
        x: jnp.ndarray,
        kv: dict,
        pos: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        layer_kinds: Optional[jnp.ndarray] = None,
        tp_axis: Optional[str] = None,
        kv_commit=None,
        sp_axis: Optional[str] = None,
        t_real=None,
    ) -> Tuple[jnp.ndarray, dict]:
        """Apply a stacked window of layers. kv holds this window's slices.

        tp_axis: mesh axis name when running tensor-parallel inside
        shard_map (params are per-device slices; reductions psum over it).
        kv_commit: optional traced bool gating cache writes (pipeline ranks
        processing a not-their-turn copy pass False).
        t_real: number of REAL (non-padding) tokens in this chunk (traced);
        models with rotating ring-buffer caches must exclude bucket padding
        from writes, because padded positions would wrap around and destroy
        live rows.  None means every token is real.
        """

    @abc.abstractmethod
    def normalize(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        """Final norm before the LM head."""

    def lm_project(self, edge_params: dict, x: jnp.ndarray) -> jnp.ndarray:
        """hidden [B, T, D] -> logits [B, T, V].

        The projection matrix is the single largest per-step HBM read at
        decode (O(hidden x vocab) — ~0.5 GB bf16 for Llama-1B); quantized
        edges (see quantize_edge) store it in [hidden, vocab] orientation so
        `dq` fuses the dequant into this matmul."""
        from dnet_tpu.ops.quant import dq, is_quantized

        if self.config.tie_word_embeddings:
            w = edge_params["embed"]["weight"]
            w = dq(w) if is_quantized(w) else w.T
        else:
            w = dq(edge_params["lm_head"]["weight"])
        return x @ w

    # ---- weight mapping ----------------------------------------------
    @abc.abstractmethod
    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """HF per-layer tensors (prefix `model.layers.{i}.` stripped) -> our
        per-layer param dict (unstacked)."""

    def map_edge(self, raw: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """HF non-layer tensors -> {"embed", "final_norm", "lm_head"}.

        Standard HF naming is shared by every supported family; override
        only for exotic edge layouts."""
        out: Dict[str, Any] = {}
        if "model.embed_tokens.weight" in raw:
            out["embed"] = {"weight": raw["model.embed_tokens.weight"]}
        if "model.norm.weight" in raw:
            out["final_norm"] = {"weight": raw["model.norm.weight"]}
        if "lm_head.weight" in raw:
            out["lm_head"] = {"weight": np.ascontiguousarray(raw["lm_head.weight"].T)}
        return out

    # ---- cache construction ------------------------------------------
    def kv_config(
        self,
        n_layers: int,
        batch: int,
        max_seq: int,
        dtype: str = "bfloat16",
        quant_bits: int = 0,
    ) -> KVConfig:
        return KVConfig(
            n_layers=n_layers,
            batch=batch,
            max_seq=max_seq,
            n_kv_heads=self.config.num_key_value_heads,
            head_dim=self.config.head_dim,
            dtype=dtype,
            quant_bits=quant_bits,
        )

    def init_kv(
        self,
        n_layers: int,
        batch: int,
        max_seq: int,
        dtype: str = "bfloat16",
        quant_bits: int = 0,
        rotating: bool = True,
    ) -> dict:
        """Allocate the stacked KV cache matching this model's window layout.

        Default: one flat [L, B, S, ...] cache.  Models with per-kind cache
        shapes (gpt_oss paired SWA ring buffers) override; `rotating=False`
        forces full-length caches (sequence-parallel serving shards the S
        axis and needs uniform length)."""
        from dnet_tpu.core.kvcache import init_cache

        return init_cache(
            self.kv_config(n_layers, batch, max_seq, dtype, quant_bits=quant_bits)
        )

    # ---- helpers ------------------------------------------------------
    @staticmethod
    def stack_layers(per_layer: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        """Stack N per-layer param dicts along a new leading axis.

        Models with heterogeneous layer structures (deepseek dense-vs-MoE)
        override this (and wrap_offload_layer) with a list layout.
        """
        if not per_layer:
            return {}
        keys = per_layer[0].keys()
        return {k: np.stack([p[k] for p in per_layer], axis=0) for k in keys}

    def quantize_params(self, stacked, bits: int, scale_dtype=None, group_size: int = 0):
        """Weight-only quantize a stacked param pytree (engine fit path).
        Default covers the flat stacked-dict layout; list-layout models
        override.  group_size=0 uses the quantizer default; tensor-parallel
        serving passes a size that divides the per-rank contraction dim."""
        from dnet_tpu.ops.quant import quantize_tree

        return quantize_tree(
            stacked, self.quant_keys, bits=bits, scale_dtype=scale_dtype,
            group_size=group_size,
        )

    def quantize_edge(self, edge: Dict[str, Any], bits: int, scale_dtype=None,
                      group_size: int = 0) -> Dict[str, Any]:
        """Quantize the LM projection among the edge params.

        Only the O(hidden x vocab) projection matrix is worth quantizing —
        it is read in full every decode step, while the embedding gather
        reads O(tokens x hidden) and the norms are vectors.  Tied embeddings
        are re-laid out to the projection orientation [hidden, vocab]
        (groups along hidden, the contraction dim); `embed_lookup` gathers
        logical table rows as physical columns from that layout, so one
        quantized array serves both ops and the bf16 table is not kept.
        """
        from dnet_tpu.ops.quant import (
            DEFAULT_GROUP,
            DEFAULT_GROUP_Q4,
            is_quantized,
            quantize_weight_q4,
            quantize_weight_q8,
        )

        if bits not in (4, 8):
            raise NotImplementedError(f"weight quantization bits={bits} (4 or 8)")
        quant = quantize_weight_q4 if bits == 4 else quantize_weight_q8
        group_size = group_size or (DEFAULT_GROUP_Q4 if bits == 4 else DEFAULT_GROUP)
        out = dict(edge)
        if self.config.tie_word_embeddings and "embed" in out:
            # tied: lm_project always reads "embed", so quantize THAT (some
            # tied checkpoints still serialize an lm_head — never read; drop)
            out.pop("lm_head", None)
            if not is_quantized(out["embed"]["weight"]):
                w = np.ascontiguousarray(np.asarray(out["embed"]["weight"]).T)
                out["embed"] = {"weight": quant(w, group_size, scale_dtype)}
        elif "lm_head" in out and not is_quantized(out["lm_head"]["weight"]):
            w = np.asarray(out["lm_head"]["weight"])  # already [hidden, vocab]
            out["lm_head"] = {"weight": quant(w, group_size, scale_dtype)}
        return out

    def wrap_offload_layer(self, mapped: Dict[str, np.ndarray]):
        """Shape ONE layer's mapped host params as a single-layer window (the
        weight-streaming unit).  Default: add the leading stack axis (tree-
        mapped so quantized {"q"/"q4","s"} leaf dicts wrap too)."""
        import jax

        return jax.tree.map(lambda v: v[None], mapped)

    def kv_rewindable(self, max_seq: int) -> bool:
        """Whether stale cache rows past a rewound `pos` are harmless.

        Slot-addressed max_seq caches qualify (stale rows are never attended
        and get overwritten); rotating ring-buffer SWA caches do not — a
        wrap-around write evicts live rows, so speculative decoding must
        refuse (see core/spec.py's KV-rewind invariant)."""
        return True

    def local_window(self, start_abs: int, size: int) -> List[int]:
        """The contiguous run of assigned layers beginning at start_abs."""
        out = []
        a = start_abs
        while a in self.abs_to_local and len(out) < size:
            out.append(a)
            a += 1
        return out
