"""Qwen3-MoE-family ring model (Qwen3-30B-A3B / 235B-A22B class).

Qwen3 attention (per-head q/k RMS norms before RoPE) + the mixtral-style
sparse MoE FFN — transformers' Qwen3MoeSparseMoeBlock is Mixtral's block
with `norm_topk_prob` read from config ("only diff with mixtral sparse
moe block"), so the whole compute path is inherited from MixtralRingModel
and only the attention hook and HF weight names differ.

Mixed dense/MoE layouts (`mlp_only_layers`, `decoder_sparse_step`) are
supported with two stacking strategies (VERDICT r3 next #6):
  - dense-PREFIX layouts (every dense layer precedes every MoE layer —
    the deepseek first_k_dense_replace shape) reuse the two-segment
    machinery wholesale: {"dense", "moe"} stacks, ring_phases=2 multi-lap
    pp rings, segment padding — full engine coverage;
  - INTERLEAVED layouts (decoder_sparse_step striding) run an
    order-preserving mixed scan: per-kind stacks plus index vectors, each
    step lax.cond-dispatching on the layer's kind — exact layer order with
    two compiled branch bodies.  pp>1 mesh rings work via CHUNK-ALIGNED
    stacking (r5, pad_mesh_segments): each rank holds its contiguous slice
    of the global order and runs the mixed scan over it, scheduled by the
    pp-sharded layer_kinds slots — a single lap reproduces the exact
    order — through the sequential mesh ring AND the staggered-microbatch
    pipelined rotation (both thread the pp-sharded kinds operand).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnet_tpu.models.base import ModelConfig, RingModel
from dnet_tpu.models.llama import LlamaRingModel
from dnet_tpu.models.mixtral import MixtralRingModel
from dnet_tpu.models.qwen3 import Qwen3RingModel
from dnet_tpu.models.segments import TwoSegmentStackMixin


class Qwen3MoeRingModel(TwoSegmentStackMixin, MixtralRingModel, Qwen3RingModel):
    """MRO: Mixtral's _mlp_block (sparse MoE) + Qwen3's _qk_transform
    (per-head q/k norms) over the shared llama decoder."""

    model_type = "qwen3_moe"
    # mixtral's expert keys plus the dense-swiglu keys mixed layouts carry
    quant_keys = MixtralRingModel.quant_keys | {"w_gate", "w_up", "w_down"}

    @property
    def supports_paged_attend(self):  # type: ignore[override]
        # uniform stacks ride llama's apply_window (attend_fn threads
        # through); the mixed two-segment scans don't carry the hook
        return not self.mixed

    def __init__(self, config: ModelConfig, layers):
        super().__init__(config, layers)
        # transformers Qwen3MoeConfig defaults norm_topk_prob to FALSE
        # (unlike mixtral, which always renormalizes)
        self.norm_topk_prob = bool(config.extra.get("norm_topk_prob", False))
        mlp_only = set(config.extra.get("mlp_only_layers") or [])
        step = config.extra.get("decoder_sparse_step", 1)

        def is_moe(a: int) -> bool:
            return a not in mlp_only and (step <= 1 or (a + 1) % step == 0)

        self.is_moe_layer = is_moe
        self.moe_mask = [is_moe(a) for a in self.layers]  # window-local
        global_kinds = [is_moe(a) for a in range(config.num_hidden_layers)]
        # degenerate all-dense / all-MoE configs are HOMOGENEOUS: the flat
        # llama-style stack handles either kind (the MLP dispatch is a
        # static dict-shape fact), no segmentation needed
        self.mixed = any(global_kinds) and not all(global_kinds)
        if self.mixed:
            # k-round stacks slice a flat layer axis; segment dicts can't
            self.segmented_stack = True
            moe_ids = [a for a, m in enumerate(global_kinds) if m]
            dense_ids = [a for a, m in enumerate(global_kinds) if not m]
            self.prefix_mixed = max(dense_ids) < min(moe_ids)
            if self.prefix_mixed:
                self.ring_phases = 2  # deepseek-style multi-lap pp rings
            else:
                # interleaved orders pp-shard via CHUNK-ALIGNED stacks (r5):
                # pad_mesh_segments reorders each kind's rows so uniform pp
                # sharding hands every rank exactly its contiguous slice of
                # the GLOBAL order, and a single lap's mixed lax.cond scan
                # (scheduled by the pp-sharded layer_kinds slots) reproduces
                # the exact layer order — in the sequential mesh ring AND
                # the staggered-microbatch rotation alike (both thread the
                # kinds operand at P(AXIS_PP)).
                self.pp_pad_chunks = True

    # ---- stacking -----------------------------------------------------
    def stack_layers(self, per_layer: List[Dict[str, np.ndarray]]):
        if not self.mixed:
            return RingModel.stack_layers(per_layer)
        dense = [p for p, m in zip(per_layer, self.moe_mask) if not m]
        moe = [p for p, m in zip(per_layer, self.moe_mask) if m]
        out: Dict[str, dict] = {}
        if dense:
            out["dense"] = RingModel.stack_layers(dense)
        if moe:
            out["moe"] = RingModel.stack_layers(moe)
        return out

    def quantize_params(self, stacked, bits: int, scale_dtype=None, group_size: int = 0):
        if not self.mixed:  # flat stack: base quantizer
            return RingModel.quantize_params(
                self, stacked, bits, scale_dtype=scale_dtype,
                group_size=group_size,
            )
        return TwoSegmentStackMixin.quantize_params(
            self, stacked, bits, scale_dtype=scale_dtype, group_size=group_size
        )

    def wrap_offload_layer(self, mapped: Dict[str, np.ndarray]):
        if not self.mixed:
            return RingModel.wrap_offload_layer(self, mapped)
        return TwoSegmentStackMixin.wrap_offload_layer(self, mapped)

    # ---- pp chunk alignment (interleaved layouts) ----------------------
    def pad_mesh_segments(self, stacked: dict, pp: int):
        """Prefix layouts: the mixin's per-segment padding (2-lap rings).
        Interleaved layouts: chunk-aligned stacking — the global layer
        order splits into pp contiguous chunks (one per pipeline rank);
        each kind's stack is laid out rank-major (a chunk's dense rows are
        already contiguous in the dense-only ordering) and padded to the
        max per-rank count with zero layers (exact residual no-ops), so
        uniform pp sharding hands every rank its own chunk.  Sets
        `self.layer_kinds` to the rank-major slot-kind schedule the mixed
        scan reads (pp-sharded operand, parallel/ring.py), and returns
        (padded_stacked, n_kv_layers = pp * slots_per_rank)."""
        if self.prefix_mixed:
            return TwoSegmentStackMixin.pad_mesh_segments(self, stacked, pp)
        L = self.config.num_hidden_layers
        C0 = -(-L // pp)
        kinds = [1 if self.is_moe_layer(a) else 0 for a in range(L)]
        kinds += [0] * (C0 * pp - L)  # virtual trailing dense no-op slots
        chunks = [kinds[r * C0 : (r + 1) * C0] for r in range(pp)]
        # real rows per rank (virtual slots own no checkpoint rows)
        real_k = [kinds[: L][r * C0 : (r + 1) * C0] for r in range(pp)]
        real_d = [c.count(0) for c in real_k]
        real_m = [c.count(1) for c in real_k]
        d_slots = [c.count(0) for c in chunks]
        m_slots = [c.count(1) for c in chunks]
        Dmax, Mmax = max(d_slots), max(m_slots)

        def chunk_pad(tree, counts, target):
            """Rank-major reorder + zero-pad one kind's stack."""
            offs = np.concatenate([[0], np.cumsum(counts)])

            def pad(a):
                rows = []
                for r in range(pp):
                    block = a[offs[r] : offs[r + 1]]
                    n = target - block.shape[0]
                    if n:
                        block = np.concatenate(
                            [block, np.zeros((n, *a.shape[1:]), a.dtype)]
                        )
                    rows.append(block)
                return np.concatenate(rows, axis=0)

            return jax.tree.map(pad, tree)

        out = {
            "dense": chunk_pad(stacked["dense"], real_d, Dmax),
            "moe": chunk_pad(stacked["moe"], real_m, Mmax),
        }
        slot_kinds = []
        for r in range(pp):
            slot_kinds += (
                chunks[r]
                + [0] * (Dmax - d_slots[r])
                + [1] * (Mmax - m_slots[r])
            )
        self.layer_kinds = jnp.asarray(slot_kinds, jnp.int32)
        return out, pp * (Dmax + Mmax)

    # ---- mixed-layout execution ---------------------------------------
    def _mlp_block(self, p: dict, x: jnp.ndarray, tp_axis=None) -> jnp.ndarray:
        # segment dispatch is static ("e_gate" in p is a dict-shape fact):
        # MoE segments take mixtral's sparse block, dense segments llama's
        if "e_gate" in p:
            return MixtralRingModel._mlp_block(self, p, x, tp_axis)
        return LlamaRingModel._mlp_block(self, p, x, tp_axis)

    def apply_window(
        self,
        window_params,
        x: jnp.ndarray,
        kv: dict,
        pos: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        layer_kinds: Optional[jnp.ndarray] = None,
        tp_axis: Optional[str] = None,
        kv_commit=None,
        sp_axis: Optional[str] = None,
        phase=None,
        t_real=None,
        attend_fn=None,
    ) -> Tuple[jnp.ndarray, dict]:
        if not self.mixed:
            return super().apply_window(
                window_params, x, kv, pos, mask=mask, layer_kinds=layer_kinds,
                tp_axis=tp_axis, kv_commit=kv_commit, sp_axis=sp_axis,
                t_real=t_real, attend_fn=attend_fn,
            )
        if attend_fn is not None:
            raise NotImplementedError(
                "paged attend_fn is not threaded through mixed-segment scans"
            )
        dense = window_params.get("dense")
        moe = window_params.get("moe")
        if self.prefix_mixed or dense is None or moe is None:
            # prefix layouts — and single-kind windows of any mixed model
            # (offload layers, shards) — run the shared two-segment scan
            # (dense then moe, missing segments no-op, phase = ring laps)
            return self._apply_segments(
                window_params, x, kv, pos, mask, tp_axis, kv_commit, sp_axis,
                phase,
            )

        # interleaved: order-preserving mixed scan over the window's layers
        if layer_kinds is not None:
            # pp mesh (chunk-aligned stacks, pad_mesh_segments): this rank's
            # slot schedule arrives as the pp-sharded kinds operand; the
            # per-kind row indices are its exclusive cumsums.  Slot j's KV
            # row is j (the chunk IS the rank's kv block).
            kinds = layer_kinds.astype(jnp.int32)
            L = kinds.shape[0]
            d_pos = jnp.cumsum(1 - kinds) - (1 - kinds)
            m_pos = jnp.cumsum(kinds) - kinds
            xs = (jnp.arange(L, dtype=jnp.int32), kinds, d_pos, m_pos)
        else:
            L = len(self.moe_mask)
            kinds = jnp.asarray([1 if m else 0 for m in self.moe_mask], jnp.int32)
            d_pos, m_pos, dc, mc = [], [], 0, 0
            for m in self.moe_mask:
                d_pos.append(dc)
                m_pos.append(mc)
                if m:
                    mc += 1
                else:
                    dc += 1
            xs = (
                jnp.arange(L, dtype=jnp.int32), kinds,
                jnp.asarray(d_pos, jnp.int32), jnp.asarray(m_pos, jnp.int32),
            )

        def body(carry, per):
            x, kv = carry
            i, kind, di, mi = per
            kv_row = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False), kv
            )

            def run_d(args):
                x, kv_row = args
                p = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, di, 0, keepdims=False),
                    dense,
                )
                return self._layer(
                    p, x, kv_row, pos, mask, tp_axis=tp_axis,
                    kv_commit=kv_commit, sp_axis=sp_axis,
                )

            def run_m(args):
                x, kv_row = args
                p = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, mi, 0, keepdims=False),
                    moe,
                )
                return self._layer(
                    p, x, kv_row, pos, mask, tp_axis=tp_axis,
                    kv_commit=kv_commit, sp_axis=sp_axis,
                )

            x, kv_row = lax.cond(kind == 1, run_m, run_d, (x, kv_row))
            kv = jax.tree.map(
                lambda f, r: lax.dynamic_update_index_in_dim(f, r, i, 0),
                kv, kv_row,
            )
            return (x, kv), None

        (x, kv), _ = lax.scan(body, (x, kv), xs)
        return x, kv

    # ---- weight mapping ------------------------------------------------
    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        def t(name: str) -> np.ndarray:
            return np.ascontiguousarray(raw[name].T)

        p: Dict[str, np.ndarray] = {
            "attn_norm": raw["input_layernorm.weight"],
            "wq": t("self_attn.q_proj.weight"),
            "wk": t("self_attn.k_proj.weight"),
            "wv": t("self_attn.v_proj.weight"),
            "wo": t("self_attn.o_proj.weight"),
            "q_norm": raw["self_attn.q_norm.weight"],
            "k_norm": raw["self_attn.k_norm.weight"],
            "mlp_norm": raw["post_attention_layernorm.weight"],
        }
        if "mlp.gate.weight" in raw:  # MoE layer
            E = self.config.num_local_experts
            p["gate_w"] = t("mlp.gate.weight")  # [D, E] router
            p["e_gate"] = np.stack(
                [t(f"mlp.experts.{e}.gate_proj.weight") for e in range(E)]
            )
            p["e_up"] = np.stack(
                [t(f"mlp.experts.{e}.up_proj.weight") for e in range(E)]
            )
            p["e_down"] = np.stack(
                [t(f"mlp.experts.{e}.down_proj.weight") for e in range(E)]
            )
        else:  # mlp_only / non-sparse-step layer: plain llama swiglu
            p["w_gate"] = t("mlp.gate_proj.weight")
            p["w_up"] = t("mlp.up_proj.weight")
            p["w_down"] = t("mlp.down_proj.weight")
        return p
