"""Qwen3-MoE-family ring model (Qwen3-30B-A3B / 235B-A22B class).

Qwen3 attention (per-head q/k RMS norms before RoPE) + the mixtral-style
sparse MoE FFN — transformers' Qwen3MoeSparseMoeBlock is Mixtral's block
with `norm_topk_prob` read from config ("only diff with mixtral sparse
moe block"), so the whole compute path is inherited from MixtralRingModel
and only the attention hook and HF weight names differ.  Supports the
homogeneous all-MoE layout (every released Qwen3-MoE checkpoint);
`mlp_only_layers` mixing dense layers in would need deepseek-style
segmented stacking and fails fast instead.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from dnet_tpu.models.base import ModelConfig
from dnet_tpu.models.mixtral import MixtralRingModel
from dnet_tpu.models.qwen3 import Qwen3RingModel


class Qwen3MoeRingModel(MixtralRingModel, Qwen3RingModel):
    """MRO: Mixtral's _mlp_block (sparse MoE) + Qwen3's _qk_transform
    (per-head q/k norms) over the shared llama decoder."""

    model_type = "qwen3_moe"

    def __init__(self, config: ModelConfig, layers):
        super().__init__(config, layers)
        # transformers Qwen3MoeConfig defaults norm_topk_prob to FALSE
        # (unlike mixtral, which always renormalizes)
        self.norm_topk_prob = bool(config.extra.get("norm_topk_prob", False))
        mlp_only = set(config.extra.get("mlp_only_layers") or [])
        step = config.extra.get("decoder_sparse_step", 1)
        dense = [
            a for a in self.layers
            if a in mlp_only or (step > 1 and (a + 1) % step != 0)
        ]
        if dense:
            raise NotImplementedError(
                f"qwen3_moe with dense layers {dense} needs segmented "
                f"stacking; only the homogeneous all-MoE layout is supported"
            )

    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        def t(name: str) -> np.ndarray:
            return np.ascontiguousarray(raw[name].T)

        E = self.config.num_local_experts
        return {
            "attn_norm": raw["input_layernorm.weight"],
            "wq": t("self_attn.q_proj.weight"),
            "wk": t("self_attn.k_proj.weight"),
            "wv": t("self_attn.v_proj.weight"),
            "wo": t("self_attn.o_proj.weight"),
            "q_norm": raw["self_attn.q_norm.weight"],
            "k_norm": raw["self_attn.k_norm.weight"],
            "mlp_norm": raw["post_attention_layernorm.weight"],
            "gate_w": t("mlp.gate.weight"),  # [D, E] router
            "e_gate": np.stack(
                [t(f"mlp.experts.{e}.gate_proj.weight") for e in range(E)]
            ),
            "e_up": np.stack(
                [t(f"mlp.experts.{e}.up_proj.weight") for e in range(E)]
            ),
            "e_down": np.stack(
                [t(f"mlp.experts.{e}.down_proj.weight") for e in range(E)]
            ),
        }
