"""Mixtral-family ring model: Llama attention + top-k sparse MoE FFN.

BASELINE config 4 names Mixtral-8x7B; the reference's model envelope covers
the same class of dense-attention MoE decoders through its catalog
(src/dnet/api/catalog.py).  Architecture (matching transformers'
MixtralForCausalLM):

- Attention is exactly Llama's (GQA + RoPE + rms norms), so the whole
  attention half — including TP head sharding, KV quant, SWA-free caches,
  sp flash-decoding, and the spec-decode rewind invariant — is inherited
  from LlamaRingModel unchanged.
- Every layer's FFN is a sparse MoE: a router linear scores E experts,
  routing weights are softmax-over-ALL-logits then top-k then renormalized
  (transformers MixtralSparseMoeBlock), and each expert is a swiglu
  (w1=gate, w3=up, w2=down).  No shared experts.
- Expert compute routes through ops/moe.moe_apply like gpt_oss/deepseek:
  dense-weighted einsum by default (exact numerics), capacity dispatch or
  all_to_all expert parallelism over the tp axis when configured.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnet_tpu.parallel.tp_collectives import tp_all_reduce
from dnet_tpu.models.llama import LlamaRingModel
from dnet_tpu.ops.norms import rms_norm


class MixtralRingModel(LlamaRingModel):
    model_type = "mixtral"
    quant_keys = frozenset(
        {"wq", "wk", "wv", "wo", "e_gate", "e_up", "e_down"}
    )  # router gate_w stays f32 (routing decisions are precision-sensitive)
    # renormalize the kept top-k weights; always on for mixtral, config-read
    # for qwen3_moe ("only diff with mixtral sparse moe block" per HF)
    norm_topk_prob = True

    def _mlp_block(self, p: dict, x: jnp.ndarray, tp_axis=None) -> jnp.ndarray:
        B, T, D = x.shape
        h = rms_norm(x, p["mlp_norm"], self.config.rms_norm_eps)
        flat = h.reshape(B * T, D)

        # transformers MixtralSparseMoeBlock: softmax over ALL logits first,
        # then top-k, then renormalize the kept weights
        logits = flat.astype(jnp.float32) @ p["gate_w"].astype(jnp.float32)
        scores = jax.nn.softmax(logits, axis=-1)  # [N, E] f32
        k = self.config.num_experts_per_tok
        top_w, top_idx = lax.top_k(scores, k)
        if self.norm_topk_prob:
            top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)
        top_idx = top_idx.astype(jnp.int32)

        from dnet_tpu.ops.moe import moe_apply, swiglu_expert_closures

        effn, dense, E_local = swiglu_expert_closures(
            p, flat, scores, top_idx, top_w, tp_axis
        )
        routed, routed_partial = moe_apply(
            self.moe_impl, flat, top_idx, top_w, effn, E_local,
            self.moe_capacity_factor, k, tp_axis, dense,
        )
        out = routed.astype(flat.dtype)
        if tp_axis is not None and routed_partial:
            # expert-combine all-reduce: the MoE twin of the dense
            # down-proj collective, routed through the quantizable seam
            out = tp_all_reduce(out, tp_axis)
        return x + out.reshape(B, T, D)

    # ---- weight mapping ----------------------------------------------
    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        def t(name: str) -> np.ndarray:
            return np.ascontiguousarray(raw[name].T)  # HF [out,in] -> (in,out)

        E = self.config.num_local_experts
        return {
            "attn_norm": raw["input_layernorm.weight"],
            "wq": t("self_attn.q_proj.weight"),
            "wk": t("self_attn.k_proj.weight"),
            "wv": t("self_attn.v_proj.weight"),
            "wo": t("self_attn.o_proj.weight"),
            "mlp_norm": raw["post_attention_layernorm.weight"],
            "gate_w": t("block_sparse_moe.gate.weight"),  # [D, E] router
            # experts stacked on a leading E axis, (in, out)-oriented
            "e_gate": np.stack(
                [t(f"block_sparse_moe.experts.{e}.w1.weight") for e in range(E)]
            ),
            "e_up": np.stack(
                [t(f"block_sparse_moe.experts.{e}.w3.weight") for e in range(E)]
            ),
            "e_down": np.stack(
                [t(f"block_sparse_moe.experts.{e}.w2.weight") for e in range(E)]
            ),
        }
