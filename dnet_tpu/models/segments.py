"""Two-segment stacked-window machinery, shared by every model whose layers
split into two param layouts: deepseek_v2 (dense prefix + MoE suffix,
first_k_dense_replace) and mixed-layout qwen3_moe (mlp_only_layers prefix).

A window stacks as {"dense": ..., "moe": ...} (either key may be absent);
execution scans the dense segment then the moe segment — correct whenever
every dense layer precedes every MoE layer in the window, which the owning
models guarantee before opting in.  On multi-lap pp rings (`ring_phases=2`)
`phase` selects one segment per lap.  The mixin expects the host class to
provide `_layer(p, x, kvs, pos, mask, tp_axis=, kv_commit=, sp_axis=)` and
a `quant_keys` set.
"""

from __future__ import annotations

from typing import Dict

import jax
import numpy as np
from jax import lax


class TwoSegmentStackMixin:
    def _scan_segment(self, seg, x, kv_seg, pos, mask, tp_axis, kv_commit, sp_axis):
        def body(carry, per_layer):
            p, kvs = per_layer
            xc, kvs = self._layer(
                p, carry, kvs, pos, mask, tp_axis=tp_axis, kv_commit=kv_commit,
                sp_axis=sp_axis,
            )
            return xc, kvs

        return lax.scan(body, x, (seg, kv_seg))

    def _apply_segments(
        self, window_params, x, kv, pos, mask, tp_axis, kv_commit, sp_axis,
        phase,
    ):
        """Dense segment then moe segment; a missing segment is a no-op
        (a shard's window may be single-kind).  `phase` (multi-lap pp ring)
        selects one segment per lap."""
        dense = window_params.get("dense")
        moe = window_params.get("moe")
        Ld = jax.tree.leaves(dense)[0].shape[0] if dense is not None else 0

        def run_dense(x, kv):
            if dense is None:
                return x, kv
            kv_seg = jax.tree.map(lambda a: a[:Ld], kv)
            x, kv_seg = self._scan_segment(
                dense, x, kv_seg, pos, mask, tp_axis, kv_commit, sp_axis
            )
            kv = jax.tree.map(lambda f, s: f.at[:Ld].set(s), kv, kv_seg)
            return x, kv

        def run_moe(x, kv):
            if moe is None:
                return x, kv
            kv_seg = jax.tree.map(lambda a: a[Ld:], kv)
            x, kv_seg = self._scan_segment(
                moe, x, kv_seg, pos, mask, tp_axis, kv_commit, sp_axis
            )
            kv = jax.tree.map(lambda f, s: f.at[Ld:].set(s), kv, kv_seg)
            return x, kv

        if phase is None:
            x, kv = run_dense(x, kv)
            return run_moe(x, kv)
        return lax.cond(
            phase == 0,
            lambda args: run_dense(*args),
            lambda args: run_moe(*args),
            (x, kv),
        )

    def quantize_params(self, stacked, bits: int, scale_dtype=None, group_size: int = 0):
        from dnet_tpu.ops.quant import quantize_tree

        return {
            seg: quantize_tree(
                tree, self.quant_keys, bits=bits, scale_dtype=scale_dtype,
                group_size=group_size,
            )
            for seg, tree in stacked.items()
        }

    def wrap_offload_layer(self, mapped: Dict[str, np.ndarray]):
        seg = "moe" if "e_gate" in mapped else "dense"
        return {seg: jax.tree.map(lambda v: v[None], mapped)}

    def pad_mesh_segments(self, stacked: dict, pp: int):
        """Zero-pad each segment's layer axis to a multiple of pp so its
        stack shards evenly over the pipeline axis.  A zero layer is an
        exact residual no-op (zero o/down/expert projections contribute
        nothing), so padded numerics are unchanged.  Returns
        (padded_stacked, n_kv_layers): the mesh KV cache is laid out
        per-rank (each rank's dense rows then its moe rows)."""

        def pad_seg(tree, target):
            def pad(a):
                n = target - a.shape[0]
                if n == 0:
                    return a
                return np.concatenate(
                    [a, np.zeros((n, *a.shape[1:]), dtype=a.dtype)], axis=0
                )

            return jax.tree.map(pad, tree)

        out = {}
        total = 0
        for seg, tree in stacked.items():
            L = jax.tree.leaves(tree)[0].shape[0]
            target = -(-L // pp) * pp  # ceil to pp multiple
            out[seg] = pad_seg(tree, target)
            total += target
        return out, total
