"""Qwen2 / Qwen2.5-family ring model.

BASELINE config 3 names Qwen2.5-32B; the reference's catalog spans the
same Qwen generations via MLX conversions (src/dnet/api/catalog.py).
Architecturally Qwen2 is the llama decoder with BIASED q/k/v projections
(o_proj and the MLP stay bias-free), so everything — attention, the
content-keyed bias mapping, TP seams, KV/weight quant, sp flash-decoding,
spec decode, pipelined serving — is inherited verbatim; the bias vectors
shard over tp like every per-head vector (parallel/mesh.py _HEAD_VECTORS).
The subclass exists to claim the `qwen2` model_type in the registry.
"""

from __future__ import annotations

from dnet_tpu.models.llama import LlamaRingModel


class Qwen2RingModel(LlamaRingModel):
    model_type = "qwen2"
