"""Qwen3-family ring model.

Same skeleton as Llama (reference mirrors this: src/dnet/core/models/
qwen3.py "Same pattern as Llama") with Qwen3's differences: per-head RMS
q/k normalization before RoPE (the `_qk_transform` hook) and an explicit
head_dim decoupled from hidden_size/num_heads.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from dnet_tpu.models.llama import LlamaRingModel
from dnet_tpu.ops.norms import rms_norm


class Qwen3RingModel(LlamaRingModel):
    model_type = "qwen3"

    def _qk_transform(self, p: dict, q: jnp.ndarray, k: jnp.ndarray):
        eps = self.config.rms_norm_eps
        return rms_norm(q, p["q_norm"], eps), rms_norm(k, p["k_norm"], eps)

    def map_layer(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        params = super().map_layer(raw)
        params["q_norm"] = raw["self_attn.q_norm.weight"]
        params["k_norm"] = raw["self_attn.k_norm.weight"]
        return params
