"""Headline benchmark: SERVED decode tokens/sec on the flagship model, real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The primary metric is the serving path — the same hot loop that backs
/v1/chat/completions: LocalEngine (chunked lax.scan decode) behind
LocalAdapter + InferenceManager, with detokenization, SSE chunk assembly,
per-request metrics, and the per-chunk host round-trip all included
(BASELINE.md declares "decode tokens/sec ... via /v1/chat/completions" as
the metric; round 1 measured only a fused microbenchmark).  A fused-scan
microbenchmark still runs for reference — `serve_vs_fused` reports how much
of the pure-device rate the served path keeps.

Config: Llama-3.2-1B-class (first BASELINE.md config), int8 weight-only
quantized (the serving configuration — pass --bf16 for unquantized),
synthetic weights (zero-egress: no checkpoint downloads), batch 1, greedy.
vs_baseline is the fraction of the single-chip HBM-bandwidth roofline
(weights read once per step: bound = batch * HBM_BW / weights_bytes).
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from typing import Optional


def _measure_fused(model, window, edge, kv, batch: int, n_steps: int = 64) -> float:
    """Pure-device ceiling: greedy decode fused into one lax.scan program."""
    import jax
    import jax.numpy as jnp

    def decode_step(window_params, edge_params, token, kv, pos):
        x = model.embed(edge_params, token)
        x, kv = model.apply_window(window_params, x, kv, pos)
        x = model.normalize(edge_params, x)
        logits = model.lm_project(edge_params, x)[:, 0]
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv

    def decode_scan(window_params, edge_params, token, kv, pos0):
        def body(carry, _):
            tok, kv, pos = carry
            tok, kv = decode_step(window_params, edge_params, tok, kv, pos)
            return (tok[:, None], kv, pos + 1), tok

        (_, kv, _), toks = jax.lax.scan(body, (token, kv, pos0), None, length=n_steps)
        return toks, kv

    step = jax.jit(decode_scan, donate_argnums=(3,))
    token = jnp.ones((batch, 1), dtype=jnp.int32)
    toks, kv = step(window, edge, token, kv, jnp.int32(0))  # warmup/compile
    toks.block_until_ready()
    # best-of-2 timed windows: the ceiling is the denominator of
    # serve_vs_fused, and a one-shot window swings +/-6% under shared-CPU
    # scheduling (r4's apparent 0.93 -> 0.86 "regression" was exactly this)
    best = 0.0
    pos = n_steps
    for _ in range(2):
        t0 = time.perf_counter()
        toks, kv = step(window, edge, token, kv, jnp.int32(pos))
        toks.block_until_ready()
        best = max(best, batch * n_steps / (time.perf_counter() - t0))
        pos += n_steps
    return best


def _measure_fused_chunks(engine, batch: int, n_steps: int = 256) -> float:
    """Pure-device ceiling for chunk-capable engines (mesh): back-to-back
    decode_chunk dispatch/read with no serving stack in the loop.  TWO warm
    calls (the second chunk still recompiles: the donated KV layout changes
    after the first) and >= 8 timed chunks, so a stray compile cannot
    dominate the window and understate the ceiling."""
    from dnet_tpu.core.types import DecodingParams

    dec = DecodingParams(temperature=0.0)
    engine.prefill("__fused__", [1, 2, 3, 4], seed=0)
    engine.decode_chunk("__fused__", 1, dec, 32)  # compile
    engine.decode_chunk("__fused__", 1, dec, 32)  # steady-state layout
    t0 = time.perf_counter()
    done = 0
    while done < n_steps:
        done += len(engine.decode_chunk("__fused__", 1, dec, 32))
    dt = time.perf_counter() - t0
    engine.end_session("__fused__")
    return batch * done / dt


def _measure_served(engine, batch: int) -> dict:
    """The declared metric: decode tok/s + TTFT through the serving stack."""
    import asyncio

    from dnet_tpu.api.inference import InferenceManager
    from dnet_tpu.api.schemas import ChatCompletionRequest
    from dnet_tpu.api.strategies import LocalAdapter
    from dnet_tpu.utils.tokenizer import ByteTokenizer

    class BenchTokenizer(ByteTokenizer):
        @property
        def eos_token_ids(self) -> set[int]:
            # unreachable id: random-weight greedy decode must never stop
            # early, so every request generates exactly max_tokens tokens
            return {-1}

    adapter = LocalAdapter(engine, chunk_size=32)
    manager = InferenceManager(adapter, request_timeout_s=600.0)
    manager.tokenizer = BenchTokenizer()
    manager.model_id = "bench"

    # 1 (prefill) + ramp 2+4+8+16 + eight full 32-chunks: long enough that
    # steady-state chunked decode dominates the ramp-up
    max_tokens = 287
    req = ChatCompletionRequest.model_validate(
        {
            "model": "bench",
            "messages": [{"role": "user", "content": "Benchmark the decode path."}],
            "max_tokens": max_tokens,
            "temperature": 0.0,
            "profile": True,
        }
    )

    async def run() -> dict:
        await adapter.start()
        metrics = []
        prompt_tokens = 0
        for i in range(4):  # request 0 is the compile warmup
            r = await manager.generate(req)
            if i == 0:
                # drop the warmup's compile-inflated observations so the
                # registry percentiles (_obs_snapshot) cover exactly the
                # timed requests, matching the medians computed below
                from dnet_tpu.obs import reset_obs

                reset_obs()
            if i > 0:
                assert r.usage.completion_tokens == max_tokens, (
                    f"expected {max_tokens} tokens, got {r.usage.completion_tokens}"
                )
                metrics.append(r.metrics)
                prompt_tokens = r.usage.prompt_tokens
        await adapter.shutdown()
        return {
            "tok_s": statistics.median(m.tps_decoding for m in metrics),
            "ttft_p50_ms": statistics.median(m.ttfb_ms for m in metrics),
            # mean live context during decode, for the MFU attention term
            "mean_ctx": prompt_tokens + max_tokens // 2,
        }

    return asyncio.run(run())


def _obs_snapshot() -> dict:
    """Histogram percentiles from the obs registry, merged into the emitted
    JSON line.  The served measurement runs through the real InferenceManager
    stack, so the registry's dnet_decode_step_ms / dnet_ttft_ms series
    already hold every step of the timed section — the artifact gains
    distribution shape (p50/p95) on top of the medians for free."""
    from dnet_tpu.obs import get_registry

    out: dict = {}
    for name, key in (
        ("dnet_decode_step_ms", "decode_step"),
        ("dnet_ttft_ms", "ttft"),
        ("dnet_prefill_ms", "prefill"),
    ):
        h = get_registry().get(name)
        if h is None or h.count == 0:
            continue
        out[f"{key}_p50_ms"] = round(h.percentile(0.5), 3)
        out[f"{key}_p95_ms"] = round(h.percentile(0.95), 3)
        out[f"{key}_n"] = int(h.count)
    return out


def _emit(out: dict, diagnostics: Optional[dict] = None) -> None:
    """Final result emission.  ONE compact JSON line on stdout — the driver
    parses exactly (and only) the last stdout line, and r4's attempts array
    grew past its capture window ("parsed": null).  Diagnostics (attempt
    logs, tracebacks, env dumps) go to stderr and a BENCH_DIAG.json side
    file instead, so they stay in the artifact trail without ever touching
    the parsed line."""
    diagnostics = diagnostics or out.pop("diagnostics", None)
    out.pop("diagnostics", None)
    if diagnostics:
        payload = json.dumps({"diagnostics": diagnostics})
        print(payload, file=sys.stderr)
        try:
            with open("BENCH_DIAG.json", "w") as f:
                f.write(payload)
        except OSError:
            pass
    print(json.dumps(out))


def _diagnostics(exc=None) -> dict:
    """Environment facts that make an accelerator-init failure debuggable
    from the BENCH artifact alone (round-2 verdicts were vacuous errors)."""
    import os
    import platform as _platform
    import traceback

    d = {
        "platform": _platform.platform(),
        "python": sys.version.split()[0],
        "jax_platforms_env": os.environ.get("JAX_PLATFORMS", ""),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
        "accel_env": {
            k: v
            for k, v in os.environ.items()
            if k.startswith(("TPU", "PJRT", "LIBTPU"))
        },
    }
    if exc is not None:
        d["init_traceback"] = "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)
        )[-2000:]
    return d


# accelerator-init strategies, attempted in order by the orchestrator
# (VERDICT r3 next #2: one failed axon probe is not evidence that NO wiring
# works).  Each entry is (name, env overrides); None deletes the var.  The
# probe subprocess replicates the bench's exact import order (dnet_tpu then
# jax.devices()), so a strategy that probes OK will also serve OK.
def _init_strategies() -> list:
    import os

    strategies = [("env-as-is", {})]
    libtpu = os.environ.get("TPU_LIBRARY_PATH", "")
    pjrt = os.environ.get("PJRT_LIBRARY_PATH", "")
    if libtpu:
        # the classic libtpu wiring: jax's own tpu backend, axon plugin out
        strategies.append(
            ("jax-tpu-libtpu", {"JAX_PLATFORMS": "tpu", "PJRT_LIBRARY_PATH": None})
        )
    if os.environ.get("JAX_PLATFORMS"):
        # plugin auto-discovery without the platform pin (identical to
        # env-as-is when no pin is exported, so only try it when one is)
        strategies.append(("jax-auto", {"JAX_PLATFORMS": None}))
    if pjrt:
        # pin the plugin platform explicitly (the axon PJRT plugin registers
        # under its own name; a bare env sometimes lacks the pin)
        strategies.append(("axon-explicit", {"JAX_PLATFORMS": "axon"}))
        if libtpu:
            # plugin-path permutation: axon .so via the TPU_LIBRARY_PATH hook
            strategies.append(
                (
                    "tpu-via-axon-lib",
                    {
                        "JAX_PLATFORMS": "tpu",
                        "TPU_LIBRARY_PATH": pjrt,
                        "PJRT_LIBRARY_PATH": None,
                    },
                )
            )
    return strategies


def _probe_mode() -> None:
    """Child: report what backend this env actually yields (one JSON line)."""
    out: dict = {}
    try:
        import dnet_tpu  # noqa: F401 - same import order as the bench

        import jax

        devs = jax.devices()
        out = {
            "ok": True,
            "backend": jax.default_backend(),
            "device_kind": getattr(devs[0], "device_kind", ""),
            "n_devices": len(devs),
        }
    except Exception as exc:
        out = {"ok": False, "error": f"{type(exc).__name__}: {exc}"[:500]}
    print(json.dumps(out))


def _build_env(overrides: dict) -> dict:
    """ONE place applying strategy env overrides (None = unset): the probe
    and the winning run must execute under byte-identical environments."""
    import os

    env = {**os.environ, "DNET_BENCH_INNER": "1"}
    for k, v in overrides.items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    return env


def _run_probe(name: str, overrides: dict, timeout_s: float) -> dict:
    """Spawn one probe attempt under its own watchdog; never raises."""
    import subprocess

    env = _build_env(overrides)
    attempt = {
        "strategy": name,
        "env": {k: (v if v is not None else "<unset>") for k, v in overrides.items()},
    }
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--probe"],
            env=env, capture_output=True, text=True, timeout=timeout_s,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
        attempt.update(json.loads(line))
    except subprocess.TimeoutExpired:
        attempt.update(ok=False, error=f"probe timed out after {timeout_s:.0f}s")
    except Exception as exc:
        attempt.update(ok=False, error=f"{type(exc).__name__}: {exc}"[:500])
    attempt["elapsed_s"] = round(time.perf_counter() - t0, 1)
    return attempt


def _orchestrate() -> None:
    """Top-level bench entry: enumerate accelerator-init strategies in probe
    subprocesses (jax backend state is sticky per process — a failed plugin
    init cannot be retried in-process), then run the real measurement under
    the first env that yields a non-CPU backend.  Every attempt's outcome
    lands in diagnostics.attempts so a vacuous BENCH artifact is impossible."""
    import os
    import subprocess

    try:
        per_probe = float(os.environ.get("DNET_BENCH_PROBE_TIMEOUT_S", "90"))
    except ValueError:
        print(json.dumps({"error": "DNET_BENCH_PROBE_TIMEOUT_S must be a number"}))
        raise SystemExit(2)
    attempts = []
    winner = None
    for name, overrides in _init_strategies():
        att = _run_probe(name, overrides, per_probe)
        attempts.append(att)
        if att.get("ok") and att.get("backend") not in ("", "cpu"):
            winner = (name, overrides, att)
            break
    if winner is not None:
        name, overrides, att = winner
        env = _build_env(overrides)
        args = [a for a in sys.argv[1:] if a != "--probe"]
        try:
            proc = subprocess.run(
                [sys.executable, __file__, *args],
                env=env, capture_output=True, text=True, timeout=3600,
            )
            line = (
                proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
            )
            out = json.loads(line)
        except Exception as exc:
            out = {"error": f"bench under {name} failed: {exc}"[:500]}
        diag = out.pop("diagnostics", {}) or {}
        diag["attempts"] = attempts
        out["init_strategy"] = name
        _emit(out, diag)
        raise SystemExit(0 if "value" in out else 1)
    # no strategy reached an accelerator: CPU fallback, with the full
    # attempt log attached (>= 3 diagnosed strategies, VERDICT r3 next #2)
    inner = _cpu_fallback_number()
    diag = {**_diagnostics(), "attempts": attempts}
    diag.update(inner.pop("diagnostics", {}) or {})
    out = {
        **inner,
        "tpu_error": "no accelerator-init strategy succeeded",
    }
    _emit(out, diag)
    raise SystemExit(0 if "value" in out else 1)


def _cpu_fallback_number() -> dict:
    """Re-exec this benchmark on the CPU backend (subprocess: the failed TPU
    init may have poisoned this process's jax state) so the bench artifact
    always carries a served number — explicitly labeled device=cpu +
    fallback=true, NOT a TPU perf claim."""
    import os
    import subprocess

    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DNET_BENCH_INNER": "1",
        "DNET_BENCH_DEVICE_TIMEOUT_S": "120",
    }
    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--smoke"],
            env=env, capture_output=True, text=True, timeout=900,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
        inner = json.loads(line)
    except Exception as exc:
        return {"cpu_fallback_error": str(exc)}
    if "value" not in inner:
        return {"cpu_fallback_error": inner.get("error", "no value")}
    inner["metric"] = str(inner.get("metric", "")) + "_cpu_fallback"
    inner["fallback"] = True
    inner["device"] = "cpu"
    return inner


def main() -> None:
    import os
    import threading

    if "--probe" in sys.argv:
        _probe_mode()
        return
    if os.environ.get("DNET_BENCH_INNER") != "1":
        _orchestrate()
        return

    import dnet_tpu  # noqa: F401 - package import re-asserts JAX_PLATFORMS
    import jax

    # fail fast (one JSON error line) instead of hanging the harness when
    # the TPU backend is unreachable; first device init can legitimately
    # take tens of seconds, so the default budget is generous
    ready = threading.Event()
    init_error: list = []

    def probe() -> None:
        try:
            jax.devices()
        except Exception as exc:  # init failure is not a hang: report it
            init_error.append(exc)
        finally:
            ready.set()

    threading.Thread(target=probe, daemon=True).start()
    try:
        budget = float(os.environ.get("DNET_BENCH_DEVICE_TIMEOUT_S", "300"))
    except ValueError:
        print(json.dumps({"error": "DNET_BENCH_DEVICE_TIMEOUT_S must be a number"}))
        raise SystemExit(2)
    failed: dict = {}
    if not ready.wait(budget):
        failed = {
            "error": "jax backend init timed out (accelerator unreachable)",
            "diagnostics": _diagnostics(),
        }
    elif init_error:
        failed = {
            "error": f"jax backend init failed: {init_error[0]}",
            "diagnostics": _diagnostics(init_error[0]),
        }
    if failed:
        if os.environ.get("DNET_BENCH_INNER") != "1":
            inner = _cpu_fallback_number()
            # fallback number first so "metric"/"value" sit at the top level;
            # the TPU failure stays in the artifact as tpu_error
            failed = {**inner, "tpu_error": failed["error"],
                      "diagnostics": failed["diagnostics"]}
        print(json.dumps(failed))
        raise SystemExit(0 if "value" in failed else 1)
    import jax.numpy as jnp

    from dnet_tpu.core.kvcache import init_cache
    from dnet_tpu.models.base import ModelConfig
    from dnet_tpu.models.llama import LlamaRingModel
    from dnet_tpu.utils.random_init import LLAMA_3_2_1B_CONFIG, random_llama_params

    bits = 0 if "--bf16" in sys.argv else (4 if "--int4" in sys.argv else 8)
    batch = 1
    if "--batch" in sys.argv:  # aggregate throughput: N sequences per step
        try:
            batch = int(sys.argv[sys.argv.index("--batch") + 1])
        except (IndexError, ValueError):
            print(json.dumps({"error": "--batch requires an integer"}))
            raise SystemExit(2)
        if batch < 1:
            print(json.dumps({"error": "--batch must be >= 1"}))
            raise SystemExit(2)
    cfg_dict = dict(LLAMA_3_2_1B_CONFIG)
    if "--smoke" in sys.argv:  # tiny shapes: code-path validation on CPU
        cfg_dict.update(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4, head_dim=16,
        )
    cfg = ModelConfig.from_hf({**cfg_dict, "architectures": []})
    layers = list(range(cfg.num_hidden_layers))
    model = LlamaRingModel(cfg, layers)
    window, edge = random_llama_params(cfg, layers, dtype="bfloat16")
    if bits:
        import numpy as _np

        from dnet_tpu.ops.quant import QUANTIZABLE, quantize_tree

        # smoke shapes have tiny contraction dims: a smaller scale-group
        # keeps groups divisible across tp ranks in --mesh mode
        group = 32 if "--smoke" in sys.argv else 0
        window = quantize_tree(
            {k: _np.asarray(v) for k, v in window.items()}, QUANTIZABLE,
            bits=bits, group_size=group,
        )
        edge = model.quantize_edge(edge, bits, group_size=group)
    # device-resident: leaving numpy here would re-upload every step
    window = jax.tree.map(jnp.asarray, window)
    edge = jax.tree.map(jnp.asarray, edge)
    max_seq = 1024

    # BEFORE any engine exists: a parity failure here flips the
    # DNET_FLASH_DECODE kill-switch that engine tracing consults
    flash_dec = _flash_decode_microbench()

    mesh_cfg = None
    if "--mesh" in sys.argv:  # e.g. --mesh 2x2 = pp2/tp2 over local devices
        try:
            pp_s, tp_s = sys.argv[sys.argv.index("--mesh") + 1].split("x")
            mesh_cfg = (int(pp_s), int(tp_s))
        except (IndexError, ValueError):
            print(json.dumps({"error": "--mesh requires PPxTP, e.g. 2x2"}))
            raise SystemExit(2)

    if mesh_cfg is not None:
        from dnet_tpu.parallel.engine import MeshEngine

        pp_n, tp_n = mesh_cfg
        engine = MeshEngine.from_params(
            cfg, window, edge, pp=pp_n, tp=tp_n, batch=batch, max_seq=max_seq,
        )
        fused_tok_s = _measure_fused_chunks(engine, batch)
        served = _measure_served(engine, batch)
    else:
        from dnet_tpu.core.engine import LocalEngine

        kv = init_cache(model.kv_config(len(layers), batch, max_seq, "bfloat16"))
        fused_tok_s = _measure_fused(model, window, edge, kv, batch)
        engine = LocalEngine.from_params(
            cfg, window, edge, batch=batch, max_seq=max_seq
        )
        served = _measure_served(engine, batch)
    obs_stats = _obs_snapshot()  # registry state right after the timed section
    tok_s = batch * served["tok_s"]  # tps_decoding is per-lane; lanes decode together

    # single-chip HBM roofline for decode: read all weights per token
    param_bytes = sum(
        int(a.size) * a.dtype.itemsize
        for a in jax.tree.leaves((window, edge))
    )
    # --smoke measures a toy config: the metric name must say so (a smoke
    # number under the llama1b name would be an actively misleading artifact)
    model_tag = "smoke" if "--smoke" in sys.argv else "llama1b"
    if mesh_cfg is not None:
        metric = "served_decode_tok_s_%s_%s_mesh_pp%dtp%d" % (
            model_tag, {0: "bf16", 4: "int4", 8: "int8"}[bits],
            mesh_cfg[0], mesh_cfg[1],
        )
    else:
        metric = "served_decode_tok_s_%s_%s_1chip" % (
            model_tag, {0: "bf16", 4: "int4", 8: "int8"}[bits]
        )
    if batch > 1:
        metric += f"_b{batch}"
    dev = jax.devices()[0]
    hbm_bw, peak_flops = CHIP_SPECS[_chip_gen(dev)]
    # weight-bound decode bound: weights are read once per STEP, so N batch
    # lanes share one read — the aggregate bound scales with batch; a mesh
    # splits the read across its chips (each reads only its shard)
    n_chips = mesh_cfg[0] * mesh_cfg[1] if mesh_cfg is not None else 1
    roofline = batch * n_chips * hbm_bw / param_bytes
    # the TPU HBM roofline is meaningless for a CPU run: re-base against
    # this device's own fused-scan ceiling so the number stays interpretable
    # instead of printing noise like 0.0002 (VERDICT r3 weak #1).  Any
    # non-cpu backend is TPU silicon here (the axon plugin registers the
    # tunneled chip under its own platform name), matching _orchestrate's
    # accelerator-win test.
    on_accel = jax.default_backend() != "cpu"
    if on_accel:
        vs_baseline = round(tok_s / roofline, 4)
        basis = "tpu_hbm_roofline"
    else:
        vs_baseline = round(tok_s / fused_tok_s, 4)
        basis = "own_fused_ceiling_cpu"
    # MFU: model FLOPs/token from the config (2 MACs per weight in every
    # matmul + the two attention matmuls over the mean live context of the
    # served run), against the chip generation's bf16 peak on TPU — or
    # against THIS device's measured matmul rate on the CPU fallback, so
    # the number never pretends a CPU run hit TPU silicon.  Decode is
    # HBM-bound, so single-chip decode MFU is expected to be small; the
    # point is roofline context the driver can judge, not a big number.
    fpt = _flops_per_token(cfg, mean_ctx=served["mean_ctx"])
    if on_accel:
        mfu = tok_s * fpt / (n_chips * peak_flops)
        mfu_basis = "chip_peak_bf16"
    else:
        from dnet_tpu.parallel.profiler import profile_device_quick

        # the forced-host "devices" of a CPU mesh share one host's cores,
        # and profile_device_quick already measures the whole host — no
        # per-chip multiply here
        mfu = tok_s * fpt / profile_device_quick()["flops_bf16"]
        mfu_basis = "measured_matmul_cpu"
    out = {
        "metric": metric,
        "value": round(tok_s, 2),
        "unit": "tok/s",
        "vs_baseline": vs_baseline,
        "vs_baseline_basis": basis,
        "fused_tok_s": round(fused_tok_s, 2),
        "serve_vs_fused": round(tok_s / fused_tok_s, 4),
        "ttft_p50_ms": round(served["ttft_p50_ms"], 1),
        "device": getattr(dev, "device_kind", "") or jax.default_backend(),
        "flops_per_token": int(fpt),
        "mfu": round(mfu, 6),
        "mfu_basis": mfu_basis,
    }
    out.update(flash_dec)
    out.update(obs_stats)
    if "--smoke" in sys.argv:
        out.update(_compress_microbench())
        if mesh_cfg is None:
            out.update(_spec_microbench(cfg, window, edge, max_seq))
    _emit(out)


def _flops_per_token(cfg, mean_ctx: int) -> float:
    """Model FLOPs per decoded token from the config alone (2 FLOPs per
    weight in every matmul — qkv/o/mlp per layer plus the lm head — and
    the two attention matmuls QK^T and PV over the mean live context).
    Independent of weight quantization: int8/int4 packing changes bytes
    read, not MACs performed.  Ref self-metrics analog:
    /root/reference/src/dnet/api/inference.py:216-233 (tokens/sec); this
    adds the FLOPs numerator the MFU judgment needs."""
    h = cfg.hidden_size
    H = cfg.num_attention_heads
    KVH = cfg.num_key_value_heads
    Hd = cfg.head_dim
    qkv = h * (H * Hd + 2 * KVH * Hd)
    o = H * Hd * h
    mlp = 3 * h * cfg.intermediate_size
    per_layer = 2 * (qkv + o + mlp) + 4 * mean_ctx * H * Hd
    lm_head = 2 * h * cfg.vocab_size
    return float(cfg.num_hidden_layers * per_layer + lm_head)


def _flash_decode_microbench() -> dict:
    """Long-cache decode attention: split-K Pallas kernel vs dense attend
    (TPU only — the kernel is ineligible on CPU).  Runs BEFORE the serving
    engine is built: a parity failure flips the DNET_FLASH_DECODE
    kill-switch so the headline number never rides a miscompiled kernel."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.default_backend() == "cpu":
        return {}
    from dnet_tpu.ops.attention import attend, causal_mask
    from dnet_tpu.ops.flash_decode import flash_decode_attend, flash_decode_eligible

    B, H, KVH, Hd, S = 1, 32, 8, 128, 32768
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, 1, H, Hd), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, KVH, Hd), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, KVH, Hd), jnp.bfloat16)
    if not flash_decode_eligible(q, k):
        return {"flash_decode": "ineligible"}
    dense = jax.jit(lambda q, k, v, p: attend(q, k, v, mask=causal_mask(1, S, p)))
    kern = jax.jit(lambda q, k, v, p: flash_decode_attend(q, k, v, p))
    out: dict = {}
    try:
        ref = np.asarray(dense(q, k, v, jnp.int32(S - 1)), np.float32)
        got = np.asarray(kern(q, k, v, jnp.int32(S - 1)), np.float32)
        err = float(np.max(np.abs(ref - got)))
        out["flash_decode_max_err"] = round(err, 5)
        if err > 3e-2:  # bf16 long-sum tolerance; beyond it = miscompile
            os.environ["DNET_FLASH_DECODE"] = "0"
            out["flash_decode"] = "parity failed; disabled for serving"
            return out
        for tag, pos in (("p2k", 2047), ("full", S - 1)):
            for name, fn in (("dense", dense), ("kernel", kern)):
                fn(q, k, v, jnp.int32(pos)).block_until_ready()  # compile
                t0 = time.perf_counter()
                for _ in range(20):
                    r = fn(q, k, v, jnp.int32(pos))
                r.block_until_ready()
                out[f"flash_decode_{name}_us_{tag}"] = round(
                    (time.perf_counter() - t0) / 20 * 1e6, 1
                )
    except Exception as exc:  # a lowering bug must not kill the headline
        os.environ["DNET_FLASH_DECODE"] = "0"
        out["flash_decode"] = f"error ({exc}); disabled for serving"[:300]
    return out


def _spec_microbench(cfg, window, edge, max_seq: int) -> dict:
    """Speculative decoding on a repetitive stream (smoke mode only): the
    verify-forward path emits 1..L+1 tokens per weight read, so accepted
    drafts multiply throughput; tokens/block records the acceptance rate
    the gain came from."""
    from dnet_tpu.core.engine import LocalEngine
    from dnet_tpu.core.types import DecodingParams

    # batch pinned to 1: speculation is a batch-1 feature (acceptance
    # length is per-lane; spec_eligible refuses larger batches), so this
    # number is per-stream regardless of the bench's --batch flag
    eng = LocalEngine.from_params(
        cfg, window, edge, batch=1, max_seq=max_seq, spec_lookahead=4
    )
    # a repeating prompt gives prompt-lookup something to look up
    ids = [1, 7, 3, 11] * 8
    dec = DecodingParams(temperature=0.0)
    eng.prefill_and_sample("warm", ids, dec)
    eng.decode_spec("warm", ids[-1], dec, 8)  # compile the verify block
    eng.decode_step("warm", ids[-1], dec)  # compile the budget<=1 fallback
    eng.end_session("warm")
    res = eng.prefill_and_sample("s", ids, dec)
    tok = int(res.token[0])
    t0 = time.perf_counter()
    emitted = blocks = 0
    while emitted < 128:
        out = eng.decode_spec("s", tok, dec, 128 - emitted)
        emitted += len(out)
        blocks += 1
        tok = int(out[-1].token[0])
    dt = time.perf_counter() - t0
    eng.end_session("s")
    out = {
        "spec_tok_s": round(emitted / dt, 2),
        "spec_tokens_per_block": round(emitted / blocks, 2),
    }

    # spec x continuous batching (r4): two repetitive lanes speculate
    # concurrently with per-lane acceptance — aggregate tok/s across lanes
    from dnet_tpu.core.batch import BatchedEngine

    beng = BatchedEngine.from_params(
        cfg, window, edge, slots=2, max_seq=max_seq, spec_lookahead=4
    )
    toks = {}
    for i in range(2):
        toks[i] = int(beng.prefill_and_sample(f"b{i}", ids, dec).token[0])

    def round_once() -> int:
        """One spec round; drains each lane's block IN ORDER so the stream
        stays real — toks[i] becomes the lane's LAST emitted token (the one
        whose hist/KV position matches the advanced pos)."""
        res, _ = beng.decode_batch(
            {f"b{i}": (toks[i], dec) for i in range(2)},
            budgets={f"b{i}": 64 for i in range(2)},
        )
        n_tok = 0
        for i in range(2):
            n = f"b{i}"
            rows = [res[n]] + beng._buffer.pop(n, [])
            toks[i] = int(rows[-1].token[0])
            n_tok += len(rows)
        return n_tok

    round_once()  # compile the verify block
    emitted = 0
    t0 = time.perf_counter()
    while emitted < 192:
        emitted += round_once()
    dt = time.perf_counter() - t0
    beng.end_session("b0")
    beng.end_session("b1")
    out["spec_batched_tok_s"] = round(emitted / dt, 2)
    return out


def _compress_microbench() -> dict:
    """DCN wire-format round-trip rates (smoke mode only).  The receive
    side is measured BOTH ways — host numpy decompress vs device-side
    dequant+scatter (the serving path) — so the artifact shows the
    receive-side improvement."""
    import jax
    import numpy as np

    from dnet_tpu.compression import (
        compress_tensor,
        decompress_tensor,
        decompress_tensor_device,
    )

    x = np.random.default_rng(0).normal(size=(1, 64, 2048)).astype(np.float32)
    out = {}
    for name, bits in (("sparse_v1", 0), ("qsparse8_v1", 8)):
        p, d, s = compress_tensor(x, 0.5, quant_bits=bits)  # warm
        jax.block_until_ready(decompress_tensor_device(p, d, s))  # compile
        t0 = time.perf_counter()
        for _ in range(5):
            p, d, s = compress_tensor(x, 0.5, quant_bits=bits)
            decompress_tensor(p, d, s)
        dt = (time.perf_counter() - t0) / 5
        t0 = time.perf_counter()
        for _ in range(5):
            decompress_tensor(p, d, s)
        host_ms = (time.perf_counter() - t0) / 5 * 1000
        t0 = time.perf_counter()
        for _ in range(5):
            jax.block_until_ready(decompress_tensor_device(p, d, s))
        dev_ms = (time.perf_counter() - t0) / 5 * 1000
        out[f"{name}_roundtrip_ms"] = round(dt * 1000, 2)
        out[f"{name}_recv_host_ms"] = round(host_ms, 2)
        out[f"{name}_recv_device_ms"] = round(dev_ms, 2)
        out[f"{name}_ratio"] = round(x.nbytes / len(p), 2)
    return out


# one row per chip generation: (HBM bandwidth B/s, bf16 peak FLOP/s) —
# _chip_gen falls back to v5e, so every lookup is total
CHIP_SPECS = {
    "v6e": (1640e9, 918e12),
    "v5e": (819e9, 197e12),
    "v5litepod": (819e9, 197e12),
    "v4": (1228e9, 275e12),
}


def _chip_gen(dev) -> str:
    kind = getattr(dev, "device_kind", "").lower()
    for gen in ("v6e", "v5e", "v5litepod", "v4"):
        if gen in kind:
            return gen
    return "v5e"


if __name__ == "__main__":
    main()
