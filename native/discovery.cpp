// dnet-tpu LAN discovery: UDP-broadcast peer announcement + peer table.
//
// Native analog of the reference's Rust dnet-p2p submodule (SURVEY.md §2.7):
// each node periodically broadcasts a small JSON announcement
// {instance, http_port, grpc_port, is_manager, slice_id} and maintains a
// table of peers seen recently (TTL-evicted).  Exposed as a C ABI for the
// Python ctypes wrapper (dnet_tpu/utils/p2p.py).
//
// Build: g++ -O2 -shared -fPIC -o libdnetdisc.so discovery.cpp -lpthread

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstring>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>

namespace {

struct Peer {
  std::string json;       // last announcement payload
  std::string addr;       // sender IP
  double last_seen;       // monotonic seconds
};

std::atomic<bool> g_running{false};
std::thread g_announce_thread;
std::thread g_listen_thread;
std::mutex g_mutex;
std::map<std::string, Peer> g_peers;  // instance -> peer
std::string g_self_json;
std::string g_self_instance;
std::string g_target = "255.255.255.255";
int g_port = 58899;
int g_interval_ms = 1000;
double g_ttl_s = 5.0;
int g_announce_fd = -1;
int g_listen_fd = -1;

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::string extract_field(const std::string& json, const std::string& key) {
  // minimal JSON string-field extraction: "key" : "value" (ws-tolerant)
  std::string pat = "\"" + key + "\"";
  auto i = json.find(pat);
  if (i == std::string::npos) return "";
  i += pat.size();
  while (i < json.size() && (json[i] == ' ' || json[i] == ':')) ++i;
  if (i >= json.size() || json[i] != '"') return "";
  ++i;  // past the opening quote of the value
  auto j = json.find('"', i);
  if (j == std::string::npos) return "";
  return json.substr(i, j - i);
}

void announce_loop() {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return;
  g_announce_fd = fd;
  int yes = 1;
  setsockopt(fd, SOL_SOCKET, SO_BROADCAST, &yes, sizeof(yes));
  sockaddr_in dst{};
  dst.sin_family = AF_INET;
  dst.sin_port = htons(g_port);
  inet_pton(AF_INET, g_target.c_str(), &dst.sin_addr);
  while (g_running.load()) {
    std::string payload;
    {
      std::lock_guard<std::mutex> lock(g_mutex);
      payload = g_self_json;
    }
    sendto(fd, payload.data(), payload.size(), 0,
           reinterpret_cast<sockaddr*>(&dst), sizeof(dst));
    std::this_thread::sleep_for(std::chrono::milliseconds(g_interval_ms));
  }
  close(fd);
  g_announce_fd = -1;
}

// Create + bind the listen socket synchronously so start() can report
// failures; the thread only consumes it.
int open_listen_socket() {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return -1;
  int yes = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &yes, sizeof(yes));
#ifdef SO_REUSEPORT
  setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &yes, sizeof(yes));
#endif
  timeval tv{0, 200000};  // 200ms poll so stop() is prompt
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(g_port);
  addr.sin_addr.s_addr = INADDR_ANY;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

void listen_loop() {
  int fd = g_listen_fd;
  if (fd < 0) return;
  char buf[2048];
  while (g_running.load()) {
    sockaddr_in src{};
    socklen_t slen = sizeof(src);
    ssize_t n = recvfrom(fd, buf, sizeof(buf) - 1, 0,
                         reinterpret_cast<sockaddr*>(&src), &slen);
    double t = now_s();
    if (n > 0) {
      buf[n] = '\0';
      std::string json(buf, static_cast<size_t>(n));
      std::string inst = extract_field(json, "instance");
      if (!inst.empty() && inst != g_self_instance) {
        char ip[INET_ADDRSTRLEN];
        inet_ntop(AF_INET, &src.sin_addr, ip, sizeof(ip));
        std::lock_guard<std::mutex> lock(g_mutex);
        g_peers[inst] = Peer{json, ip, t};
      }
    }
    // TTL eviction
    std::lock_guard<std::mutex> lock(g_mutex);
    for (auto it = g_peers.begin(); it != g_peers.end();) {
      if (t - it->second.last_seen > g_ttl_s)
        it = g_peers.erase(it);
      else
        ++it;
    }
  }
  close(fd);
  g_listen_fd = -1;
}

}  // namespace

extern "C" {

// Start announcing + listening. announcement_json must contain
// "instance":"...". Returns 0 on success.
int dnet_disc_start(const char* announcement_json, const char* target_addr,
                    int udp_port, int interval_ms, double ttl_s) {
  if (g_running.load()) return 1;
  {
    std::lock_guard<std::mutex> lock(g_mutex);
    g_self_json = announcement_json ? announcement_json : "";
    g_self_instance = extract_field(g_self_json, "instance");
    if (target_addr && target_addr[0]) g_target = target_addr;
    g_port = udp_port > 0 ? udp_port : 58899;
    g_interval_ms = interval_ms > 0 ? interval_ms : 1000;
    g_ttl_s = ttl_s > 0 ? ttl_s : 5.0;
    g_peers.clear();
  }
  g_listen_fd = open_listen_socket();
  if (g_listen_fd < 0) return -1;  // bind failed: report, don't run half-blind
  g_running.store(true);
  g_listen_thread = std::thread(listen_loop);
  g_announce_thread = std::thread(announce_loop);
  return 0;
}

// Update our announcement payload (e.g. is_busy flips).
void dnet_disc_update(const char* announcement_json) {
  std::lock_guard<std::mutex> lock(g_mutex);
  g_self_json = announcement_json ? announcement_json : g_self_json;
}

// Write the peer table as a JSON array into buf; returns bytes needed
// (call with buf=nullptr to size, like snprintf).
int dnet_disc_peers(char* buf, int buflen) {
  std::ostringstream os;
  {
    std::lock_guard<std::mutex> lock(g_mutex);
    os << "[";
    bool first = true;
    for (auto& kv : g_peers) {
      if (!first) os << ",";
      first = false;
      // splice the sender address into the payload object
      const std::string& j = kv.second.json;
      if (!j.empty() && j.back() == '}') {
        os << j.substr(0, j.size() - 1) << ",\"addr\":\"" << kv.second.addr
           << "\"}";
      } else {
        os << j;
      }
    }
    os << "]";
  }
  std::string out = os.str();
  int needed = static_cast<int>(out.size()) + 1;
  if (buf && buflen >= needed) std::memcpy(buf, out.c_str(), needed);
  return needed;
}

void dnet_disc_stop() {
  if (!g_running.exchange(false)) return;
  if (g_announce_thread.joinable()) g_announce_thread.join();
  if (g_listen_thread.joinable()) g_listen_thread.join();
  std::lock_guard<std::mutex> lock(g_mutex);
  g_peers.clear();
}

}  // extern "C"
