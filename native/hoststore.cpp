// Native host weight store: mmap + madvise streaming for checkpoint files.
//
// The TPU-native equivalent of the reference's native disk->memory path
// (src/dnet/utils/layer_manager.py:107-286 drives libc madvise through
// ctypes; the Rust/native submodules own the performance-critical IO).
// Here the whole subsystem is C++ with a C ABI consumed via ctypes
// (dnet_tpu/utils/native_store.py):
//
//   - hs_open / hs_close        mmap a safetensors file read-only
//   - hs_addr / hs_size         base pointer for zero-copy numpy views
//   - hs_prefetch               madvise(MADV_WILLNEED) on page-aligned spans
//   - hs_prefetch_async         background readahead thread: WILLNEED then
//                               touch one byte per page, forcing the read
//                               to overlap device compute (the reference's
//                               prefetch thread pool, layer_manager.py:284)
//   - hs_release                madvise(MADV_DONTNEED): drop evicted
//                               windows' pages (layer_manager.py:217-227)
//   - hs_read                   bounded memcpy out of the map
//   - hs_pending                in-flight async prefetch spans (tests/obs)
//
// No JAX/Python types cross this boundary: offsets+lengths in, pages ready
// or bytes out.  Thread-safe: a global handle table under one mutex, one
// detached worker draining a condition-variable queue.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapping {
  void* base = nullptr;
  uint64_t size = 0;
  int fd = -1;
};

struct Span {
  int handle;
  uint64_t off;
  uint64_t len;
};

// Intentionally leaked: the detached worker may still be running when
// exit() destroys statics — it blocks on the cv (glibc pthread_cond_destroy
// waits for waiters: deadlock) and locks g_mu / reads g_maps via lookup()
// (use-after-destroy).  Never destructing any of them keeps exit safe.
std::mutex& g_mu = *new std::mutex();
std::unordered_map<int, Mapping>& g_maps = *new std::unordered_map<int, Mapping>();
int g_next_handle = 1;

std::mutex& g_q_mu = *new std::mutex();
std::condition_variable& g_q_cv = *new std::condition_variable();
std::deque<Span>& g_queue = *new std::deque<Span>();
std::atomic<int> g_pending{0};
std::atomic<bool> g_worker_up{false};

long page_size() {
  static long ps = sysconf(_SC_PAGESIZE);
  return ps;
}

// Clamp [off, off+len) to the mapping and page-align outward.
bool aligned_span(const Mapping& m, uint64_t off, uint64_t len, char** start,
                  size_t* n) {
  if (off >= m.size || len == 0) return false;
  if (off + len > m.size) len = m.size - off;
  const uint64_t ps = static_cast<uint64_t>(page_size());
  uint64_t a = off / ps * ps;
  uint64_t b = (off + len + ps - 1) / ps * ps;
  if (b > m.size) b = m.size;
  *start = static_cast<char*>(m.base) + a;
  *n = static_cast<size_t>(b - a);
  return true;
}

bool lookup(int h, Mapping* out) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_maps.find(h);
  if (it == g_maps.end()) return false;
  *out = it->second;
  return true;
}

void worker_main() {
  for (;;) {
    Span s;
    {
      std::unique_lock<std::mutex> lk(g_q_mu);
      g_q_cv.wait(lk, [] { return !g_queue.empty(); });
      s = g_queue.front();
      g_queue.pop_front();
    }
    Mapping m;
    if (lookup(s.handle, &m)) {
      char* start;
      size_t n;
      if (aligned_span(m, s.off, s.len, &start, &n)) {
        madvise(start, n, MADV_WILLNEED);
        // Touch one byte per page: WILLNEED is only a hint, the touch
        // guarantees the read happens HERE (overlapped with compute)
        // instead of at first use on the hot path.
        volatile char sink = 0;
        const long ps = page_size();
        for (size_t i = 0; i < n; i += static_cast<size_t>(ps)) sink ^= start[i];
        (void)sink;
      }
    }
    g_pending.fetch_sub(1, std::memory_order_relaxed);
  }
}

void ensure_worker() {
  bool expected = false;
  if (g_worker_up.compare_exchange_strong(expected, true)) {
    std::thread(worker_main).detach();
  }
}

}  // namespace

extern "C" {

int hs_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    close(fd);
    return -1;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return -1;
  }
  // Random-access pattern by default: layer reads jump between tensor
  // spans, so kernel readahead across the whole file wastes page cache.
  madvise(base, static_cast<size_t>(st.st_size), MADV_RANDOM);
  std::lock_guard<std::mutex> lk(g_mu);
  int h = g_next_handle++;
  g_maps[h] = Mapping{base, static_cast<uint64_t>(st.st_size), fd};
  return h;
}

void hs_close(int handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_maps.find(handle);
  if (it == g_maps.end()) return;
  munmap(it->second.base, static_cast<size_t>(it->second.size));
  close(it->second.fd);
  g_maps.erase(it);
}

uint64_t hs_size(int handle) {
  Mapping m;
  return lookup(handle, &m) ? m.size : 0;
}

void* hs_addr(int handle) {
  Mapping m;
  return lookup(handle, &m) ? m.base : nullptr;
}

int hs_prefetch(int handle, uint64_t off, uint64_t len) {
  Mapping m;
  if (!lookup(handle, &m)) return -1;
  char* start;
  size_t n;
  if (!aligned_span(m, off, len, &start, &n)) return -1;
  return madvise(start, n, MADV_WILLNEED);
}

int hs_prefetch_async(int handle, uint64_t off, uint64_t len) {
  Mapping m;
  if (!lookup(handle, &m)) return -1;
  ensure_worker();
  g_pending.fetch_add(1, std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> lk(g_q_mu);
    g_queue.push_back(Span{handle, off, len});
  }
  g_q_cv.notify_one();
  return 0;
}

int hs_release(int handle, uint64_t off, uint64_t len) {
  Mapping m;
  if (!lookup(handle, &m)) return -1;
  char* start;
  size_t n;
  if (!aligned_span(m, off, len, &start, &n)) return -1;
  return madvise(start, n, MADV_DONTNEED);
}

int hs_read(int handle, uint64_t off, uint64_t len, void* dst) {
  Mapping m;
  if (!lookup(handle, &m)) return -1;
  if (off >= m.size || off + len > m.size) return -1;
  memcpy(dst, static_cast<char*>(m.base) + off, static_cast<size_t>(len));
  return 0;
}

int hs_pending() { return g_pending.load(std::memory_order_relaxed); }

}  // extern "C"
