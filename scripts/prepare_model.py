#!/usr/bin/env python
"""Prepare-and-load convenience: download (when the hub is reachable),
optionally repack per-layer files for weight streaming, then ask a running
API node to load the model.

Reference analog: scripts/prepare_model.py (download + load in one step).

Examples:
  python scripts/prepare_model.py Qwen/Qwen3-4B --api http://localhost:8080
  python scripts/prepare_model.py Llama-3.2-1B-Instruct:int8 --repack
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import urllib.request
from pathlib import Path


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("model", help="catalog id, optionally with :int8/:int4 variant")
    p.add_argument("--models-dir", default="~/.dnet-tpu/models")
    p.add_argument("--api", default="", help="API base URL to POST /v1/load_model to")
    p.add_argument(
        "--repack", action="store_true",
        help="pre-split per-layer files for the weight-streaming fast path",
    )
    p.add_argument("--max-seq", type=int, default=0)
    args = p.parse_args()

    from dnet_tpu.api.catalog import resolve_variant

    resolved = resolve_variant(args.model)
    if resolved is None:
        print(f"unknown catalog model/variant: {args.model}", file=sys.stderr)
        return 2
    entry, quant_bits = resolved

    models_dir = Path(args.models_dir).expanduser()
    dest = models_dir / entry.id.replace("/", "--")
    if not dest.is_dir():
        rc = subprocess.call(
            [
                sys.executable,
                str(Path(__file__).parent / "download_model.py"),
                entry.id,
                "--models-dir",
                str(models_dir),
            ]
        )
        if rc != 0:
            return rc

    if args.repack:
        rc = subprocess.call(
            [
                sys.executable,
                str(Path(__file__).parent / "repack_layers.py"),
                str(dest),
            ]
        )
        if rc != 0:
            return rc

    if args.api:
        body = {"model": str(dest)}
        if args.max_seq:
            body["max_seq_len"] = args.max_seq
        if quant_bits:
            body["weight_quant_bits"] = quant_bits
        req = urllib.request.Request(
            args.api.rstrip("/") + "/v1/load_model",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=600) as r:
            print(r.read().decode())
    else:
        hint = {"model": str(dest)}
        if quant_bits:
            hint["weight_quant_bits"] = quant_bits
        print(f"prepared {dest}\nload with: POST /v1/load_model {json.dumps(hint)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
