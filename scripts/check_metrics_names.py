#!/usr/bin/env python
"""Metric-name lint CLI shim.

The passes (registry names, source-literal scan, federation round
trip, paged-pool conservation, chaos-point coverage, admission /
membership / attribution / sanitizer / scheduler label cross-checks)
moved into the static analysis framework as checks DL010-DL019 —
``dnet_tpu/analysis/metrics_checks.py`` — where they run alongside the
async-safety / JIT-purity / contract checks via ``scripts/dnetlint.py``
and the tier-1 wrapper (tests/test_static_analysis.py).

This shim keeps the historical entry point and output format byte-stable:
``python scripts/check_metrics_names.py`` exits 0 with the ``ok: ...``
summary on a clean tree, prints ``FAIL ...`` lines and exits 1 otherwise
(tests/test_metrics_lint.py pins this contract).
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # runnable as `python scripts/check_...py`
    sys.path.insert(0, str(REPO))

from dnet_tpu.analysis.metrics_checks import (  # noqa: E402,F401 — re-exported
    _CALL_RE,
    _HELP_RE,
    _REQUIRED_FAMILIES,
    _check_name,
    _cross_check_labels,
    check_admission_labels,
    check_attribution_labels,
    check_chaos_points,
    check_federation,
    check_fleet_labels,
    check_membership_labels,
    check_paged_conservation,
    check_registry,
    check_event_labels,
    check_san_labels,
    check_sched_labels,
    check_sources,
    check_wire_labels,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
