#!/usr/bin/env python
"""Metric-name lint: every dnet metric matches `dnet_[a-z0-9_]+` and has a
help string.

Three passes, so drift cannot hide any way:

1. **Live registry** — import `dnet_tpu.obs` (which registers the canonical
   family set) and validate every registered family's name and help.
2. **Source scan** — regex over the tree for `counter(` / `gauge(` /
   `histogram(` calls whose first argument is a string literal, catching
   series that a future PR registers lazily (never hit by pass 1) or with
   an empty/missing help string.
3. **Federation round trip** — relabel the live registry's exposition under
   two node ids and merge (obs/federation.py, the `/v1/cluster/metrics`
   path): every sample must re-parse with a valid family name and carry
   exactly one `node` label, HELP/TYPE must emit once per family, and the
   cluster-scope families this surface depends on (`dnet_slo_*`,
   `dnet_prefix_refill_total`, `dnet_federation_scrape_ok`) must exist.

Invoked from the tier-1 suite (tests/test_metrics_lint.py) so a bad name
fails CI, not a 3am dashboard.  Exit 0 = clean, 1 = violations (printed).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # runnable as `python scripts/check_...py`
    sys.path.insert(0, str(REPO))

# metric-registration calls with a literal name; help must be the next
# argument and a non-empty string literal
_CALL_RE = re.compile(
    r"""\.\s*(counter|gauge|histogram)\(\s*
        (?P<q>['"])(?P<name>[^'"]+)(?P=q)\s*,\s*
        (?P<rest>.{0,120})""",
    re.VERBOSE | re.DOTALL,
)
_HELP_RE = re.compile(r"""^(?P<q>['"])(?P<help>[^'"]*)""")

_SCAN_DIRS = ("dnet_tpu", "scripts")
_SCAN_FILES = ("bench.py",)


def _check_name(name: str, where: str, errors: list) -> None:
    from dnet_tpu.obs import METRIC_NAME_RE

    if not METRIC_NAME_RE.match(name):
        errors.append(
            f"{where}: metric name {name!r} does not match "
            f"{METRIC_NAME_RE.pattern}"
        )


def check_registry(errors: list) -> int:
    from dnet_tpu.obs import get_registry

    fams = get_registry().families()
    for name, fam in fams.items():
        _check_name(name, "registry", errors)
        if not fam.help.strip():
            errors.append(f"registry: metric {name} has an empty help string")
    return len(fams)


def check_sources(errors: list) -> int:
    n = 0
    files = [REPO / f for f in _SCAN_FILES]
    for d in _SCAN_DIRS:
        files.extend(sorted((REPO / d).rglob("*.py")))
    for path in files:
        if not path.is_file():
            continue
        text = path.read_text()
        for m in _CALL_RE.finditer(text):
            name = m.group("name")
            if not name.startswith("dnet_"):
                continue  # not one of ours (e.g. a generic helper call)
            n += 1
            where = f"{path.relative_to(REPO)}"
            _check_name(name, where, errors)
            hm = _HELP_RE.match(m.group("rest").lstrip())
            if hm is None or not hm.group("help").strip():
                errors.append(
                    f"{where}: metric {name} registered without a literal "
                    f"non-empty help string"
                )
    return n


# families the cluster observability surface registers; their absence means
# a refactor silently dropped a series dashboards/alerts depend on
_REQUIRED_FAMILIES = (
    "dnet_slo_ttft_p95_ms",
    "dnet_slo_decode_p95_ms",
    "dnet_slo_availability",
    "dnet_slo_burning",
    "dnet_prefix_refill_total",
    "dnet_federation_scrape_ok",
    # paged KV pool (dnet_tpu/kv/) — capacity dashboards and the
    # backpressure alert depend on these
    "dnet_kv_blocks_used",
    "dnet_kv_blocks_free",
    "dnet_kv_pool_blocks",
    "dnet_kv_cow_copies_total",
    "dnet_kv_prefix_shared_blocks_total",
    "dnet_kv_admission_rejected_total",
    # resilience (dnet_tpu/resilience/) — the retry/resume dashboards and
    # the chaos-coverage lint (pass 5) depend on these
    "dnet_rpc_retries_total",
    "dnet_stream_reopens_total",
    "dnet_request_resumed_total",
    "dnet_resume_replay_tokens_total",
    "dnet_chaos_injected_total",
    # admission / overload survival (dnet_tpu/admission/) — the shed-rate
    # alert, drain runbook, and the label cross-check (pass 6) depend on
    # these
    "dnet_admit_queue_depth",
    "dnet_admit_inflight",
    "dnet_admit_admitted_total",
    "dnet_admit_wait_ms",
    "dnet_admit_rejected_total",
    "dnet_deadline_exceeded_total",
    "dnet_cancel_propagated_total",
    "dnet_drain_state",
    "dnet_shard_outq_dropped_total",
    # elastic ring membership (dnet_tpu/membership/) — the epoch-fence
    # dashboards, recovery alert, and the label cross-check (pass 7)
    # depend on these
    "dnet_topology_epoch",
    "dnet_stale_epoch_rejected_total",
    "dnet_recovery_total",
    "dnet_recovery_duration_seconds",
    "dnet_shard_rejoins_total",
    # performance attribution (obs/phases.py, obs/jit.py) — the loadgen
    # report's phase/JIT/memory sections and the p99 cross-check (pass 8)
    # depend on these
    "dnet_step_phase_ms",
    "dnet_jit_compiles_total",
    "dnet_jit_compile_ms",
    "dnet_device_mem_bytes",
    "dnet_slo_ttft_p99_ms",
    "dnet_slo_decode_p99_ms",
)


def check_federation(errors: list) -> int:
    """Pass 3: federate the live exposition with itself under two node ids
    and re-validate the merged document sample by sample."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.obs.federation import _SAMPLE_RE, _family_of, federate

    fams = get_registry().families()
    for req in _REQUIRED_FAMILIES:
        if req not in fams:
            errors.append(f"federation: required family {req} not registered")
    text = get_registry().expose()
    merged, skipped = federate([("api", text), ("shard-0", text)])
    for line in skipped:
        errors.append(f"federation: dropped unparseable line {line!r}")
    n = 0
    typed: set = set()
    for line in merged.splitlines():
        if line.startswith("# TYPE "):
            name = line.split()[2]
            if name in typed:
                errors.append(f"federation: duplicate TYPE for {name}")
            typed.add(name)
            continue
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"federation: emitted unparseable sample {line!r}")
            continue
        n += 1
        _check_name(_family_of(m.group("name")), "federation", errors)
        if line.count('node="') != 1:
            errors.append(
                f"federation: sample must carry exactly one node label: "
                f"{line!r}"
            )
    return n


def check_paged_conservation(errors: list) -> int:
    """Pass 4: exercise the paged KV pool through an alloc / share / COW /
    table-release / prefix-eviction script and assert the books balance at
    every step — used + free == pool (shared blocks counted once), the
    free list stays duplicate-free and disjoint, refcounts match holders,
    and the gauges report exactly what the pool says."""
    from dnet_tpu.kv import BlockPool, KVPoolExhausted, PagedKVConfig, PageTable
    from dnet_tpu.obs import metric

    pool = BlockPool(PagedKVConfig(block_tokens=8, pool_blocks=12))
    steps = 0

    def audit(holders):
        nonlocal steps
        steps += 1
        try:
            pool.check_conservation(holders)
        except AssertionError as exc:
            errors.append(f"paged-conservation step {steps}: {exc}")
            return
        used = metric("dnet_kv_blocks_used").value
        free = metric("dnet_kv_blocks_free").value
        if (used, free) != (pool.used, pool.free):
            errors.append(
                f"paged-conservation step {steps}: gauges ({used}, {free}) "
                f"!= pool ({pool.used}, {pool.free})"
            )

    t1, t2 = PageTable(), PageTable()
    entry = pool.alloc(2)  # a prefix entry's blocks
    audit([entry])
    pool.ensure(t1, 20)  # 3 blocks
    audit([entry, t1.blocks])
    t2.blocks.extend(pool.share(entry))  # adoption aliases the entry
    pool.ensure(t2, 30)  # grows past the shared run
    audit([entry, t1.blocks, entry, t2.blocks[2:]])
    old = t2.blocks[1]
    t2.blocks[1] = pool.cow(old)  # diverge mid-run
    audit([entry, t1.blocks, [entry[0]], t2.blocks[1:]])
    try:
        pool.alloc(pool.free + 1)
        errors.append("paged-conservation: overdraw did not raise")
    except KVPoolExhausted:
        pass
    audit([entry, t1.blocks, [entry[0]], t2.blocks[1:]])
    pool.release_table(t1)
    pool.release_table(t2)
    pool.free_blocks(entry)  # prefix eviction
    audit([])
    if pool.used != 0 or pool.free != pool.total:
        errors.append(
            f"paged-conservation: end state leaks ({pool.used} used, "
            f"{pool.free}/{pool.total} free)"
        )
    return steps


def check_chaos_points(errors: list) -> int:
    """Pass 5: every chaos injection point declared in
    dnet_tpu/resilience/chaos.py must have a pre-touched
    dnet_chaos_injected_total{point=} series — a new point cannot ship
    without its observability, and a renamed point cannot strand a stale
    label."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.resilience.chaos import INJECTION_POINTS

    text = get_registry().expose()
    n = 0
    for point in INJECTION_POINTS:
        n += 1
        if f'dnet_chaos_injected_total{{point="{point}"}}' not in text:
            errors.append(
                f"chaos: injection point {point!r} has no "
                f"dnet_chaos_injected_total label (pre-touch it in "
                f"dnet_tpu.obs._register_core)"
            )
    # reverse direction: no exposed point label without a declaration
    import re

    for m in re.finditer(
        r'dnet_chaos_injected_total\{point="([^"]+)"\}', text
    ):
        if m.group(1) not in INJECTION_POINTS:
            errors.append(
                f"chaos: exposed point label {m.group(1)!r} is not declared "
                f"in chaos.INJECTION_POINTS"
            )
    return n


def _cross_check_labels(
    errors: list, text: str, family: str, label: str, declared, where: str
) -> int:
    """Exposed `family{label=...}` series must match `declared` EXACTLY in
    both directions: every declared value pre-touched, no stray label."""
    import re

    n = 0
    scope = where.split(".", 1)[0]
    for value in declared:
        n += 1
        if f'{family}{{{label}="{value}"}}' not in text:
            errors.append(
                f"{scope}: {where} value {value!r} has no {family} "
                f"series (pre-touch it in dnet_tpu.obs._register_core)"
            )
    for m in re.finditer(rf'{family}\{{{label}="([^"]+)"\}}', text):
        if m.group(1) not in declared:
            errors.append(
                f"{scope}: exposed {family} {label} label "
                f"{m.group(1)!r} is not declared in {where}"
            )
    return n


def check_admission_labels(errors: list) -> int:
    """Pass 6: the admission surface's labeled families must agree with
    the declared enums (dnet_tpu/admission/reasons.py) both ways — a new
    reject reason or deadline stage cannot ship without its series, and a
    renamed one cannot strand a stale label on dashboards."""
    from dnet_tpu.admission.reasons import DEADLINE_STAGES, REJECT_REASONS
    from dnet_tpu.obs import get_registry

    text = get_registry().expose()
    n = _cross_check_labels(
        errors, text, "dnet_admit_rejected_total", "reason",
        REJECT_REASONS, "admission.reasons.REJECT_REASONS",
    )
    n += _cross_check_labels(
        errors, text, "dnet_deadline_exceeded_total", "stage",
        DEADLINE_STAGES, "admission.reasons.DEADLINE_STAGES",
    )
    return n


def check_membership_labels(errors: list) -> int:
    """Pass 7: the membership surface's labeled families must agree with
    the declared enums (dnet_tpu/membership/epoch.py) both ways — a new
    stale-epoch kind or recovery outcome cannot ship without its series,
    and a renamed one cannot strand a stale label on dashboards.  Same
    pattern as passes 5-6."""
    from dnet_tpu.membership.epoch import RECOVERY_OUTCOMES, STALE_EPOCH_KINDS
    from dnet_tpu.obs import get_registry

    text = get_registry().expose()
    n = _cross_check_labels(
        errors, text, "dnet_stale_epoch_rejected_total", "kind",
        STALE_EPOCH_KINDS, "membership.epoch.STALE_EPOCH_KINDS",
    )
    n += _cross_check_labels(
        errors, text, "dnet_recovery_total", "outcome",
        RECOVERY_OUTCOMES, "membership.epoch.RECOVERY_OUTCOMES",
    )
    return n


def check_attribution_labels(errors: list) -> int:
    """Pass 8: the performance-attribution families must agree with the
    declared enums (dnet_tpu/obs/phases.py) both ways.  Histogram families
    expose per-label `_bucket`/`_sum`/`_count` series, so presence is
    checked on `_count` and strays on any exposition suffix."""
    import re

    from dnet_tpu.obs import get_registry
    from dnet_tpu.obs.phases import DEVICE_MEM_KINDS, JIT_FNS, STEP_PHASES

    text = get_registry().expose()
    n = 0
    for phase in STEP_PHASES:
        n += 1
        if f'dnet_step_phase_ms_count{{phase="{phase}"}}' not in text:
            errors.append(
                f"attribution: obs.phases.STEP_PHASES value {phase!r} has "
                f"no dnet_step_phase_ms series (pre-touch it in "
                f"dnet_tpu.obs._register_core)"
            )
    for m in re.finditer(
        r'dnet_step_phase_ms(?:_bucket|_sum|_count)\{phase="([^"]+)"', text
    ):
        if m.group(1) not in STEP_PHASES:
            errors.append(
                f"attribution: exposed dnet_step_phase_ms phase label "
                f"{m.group(1)!r} is not declared in obs.phases.STEP_PHASES"
            )
    n += _cross_check_labels(
        errors, text, "dnet_jit_compiles_total", "fn",
        JIT_FNS, "obs.phases.JIT_FNS",
    )
    n += _cross_check_labels(
        errors, text, "dnet_device_mem_bytes", "kind",
        DEVICE_MEM_KINDS, "obs.phases.DEVICE_MEM_KINDS",
    )
    return n


def main() -> int:
    errors: list[str] = []
    n_reg = check_registry(errors)
    n_src = check_sources(errors)
    n_fed = check_federation(errors)
    n_pool = check_paged_conservation(errors)
    n_chaos = check_chaos_points(errors)
    n_admit = check_admission_labels(errors)
    n_member = check_membership_labels(errors)
    n_attr = check_attribution_labels(errors)
    if errors:
        for e in errors:
            print(f"FAIL {e}")
        return 1
    print(f"ok: {n_reg} registered families, {n_src} source-literal "
          f"registrations, {n_fed} federated samples, {n_pool} paged-pool "
          f"audits, {n_chaos} chaos points, {n_admit} admission labels, "
          f"{n_member} membership labels, {n_attr} attribution labels, "
          f"all conform")
    return 0


if __name__ == "__main__":
    sys.exit(main())
