#!/usr/bin/env python
"""dnetlint: repo-native static analysis for async-safety, JIT purity,
and contract drift (dnet_tpu/analysis/).

Usage::

    python scripts/dnetlint.py                  # full run, exit 1 on findings
    python scripts/dnetlint.py --ast-only       # skip runtime metric passes
    python scripts/dnetlint.py --select DL006   # one check
    python scripts/dnetlint.py --diff HEAD      # only files changed vs HEAD
                                                # (pre-commit mode: AST-only,
                                                # exit 1 on new findings)
    python scripts/dnetlint.py --json           # also write ANALYSIS_r<NN>.json
    python scripts/dnetlint.py --json out.json  # ...to an explicit path
    python scripts/dnetlint.py --write-baseline # grandfather current findings
    python scripts/dnetlint.py --list-checks    # catalog

Inline suppression (reason mandatory)::

    something_flagged()  # dnetlint: disable=DL005 calibration probe: the sync IS the measurement

Baseline: ``.dnetlint-baseline`` at the repo root — grandfathered
fingerprints, one per line, each with a justification.  Stale entries
fail the run, so the file cannot rot.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    from dnet_tpu.analysis import (
        ALL_CHECKS,
        DEFAULT_BASELINE,
        next_report_path,
        run_analysis,
        write_baseline,
        write_report_json,
    )
    from dnet_tpu.analysis.core import changed_files

    ap = argparse.ArgumentParser(
        prog="dnetlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--ast-only", action="store_true",
                    help="skip runtime passes (DL010+); pure-AST run")
    ap.add_argument("--select", default="",
                    help="comma-separated DL codes to run (default: all); "
                         "unknown codes are an error (exit 2)")
    ap.add_argument("--diff", metavar="REV", default=None,
                    help="lint only .py files changed vs REV (working tree "
                         "+ untracked, via git); implies --ast-only — the "
                         "fast pre-commit mode.  Cross-file checks still "
                         "see the whole tree, so diff findings agree with "
                         "a full run's for the same files")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline path (default: {DEFAULT_BASELINE})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--json", nargs="?", const="auto", default=None,
                    metavar="PATH",
                    help="write a JSON report (default path: next "
                         "ANALYSIS_r<NN>.json beside the BENCH records)")
    ap.add_argument("--list-checks", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.list_checks:
        for c in ALL_CHECKS:
            kind = "runtime" if c.requires_runtime else "ast"
            print(f"{c.code}  {c.name:28s} [{kind:7s}] {c.description}")
        # the dsan catalog: detectors that only fire in a RUNNING process
        # (DNET_SAN=1); their findings merge into --json's runtime section
        from dnet_tpu.analysis.runtime import RUNTIME_CHECKS

        for code, name, description in RUNTIME_CHECKS:
            print(f"{code}  {name:28s} [dsan   ] {description}")
        return 0

    checks = ALL_CHECKS
    if args.select:
        wanted = {c.strip().upper() for c in args.select.split(",") if c.strip()}
        known = {c.code for c in ALL_CHECKS}
        unknown = sorted(wanted - known)
        if unknown:
            print(
                f"dnetlint: unknown check code(s) {', '.join(unknown)}; "
                f"known codes: {', '.join(sorted(known))}",
                file=sys.stderr,
            )
            return 2
        checks = [c for c in ALL_CHECKS if c.code in wanted]
    if args.ast_only or args.diff is not None:
        checks = [c for c in checks if not c.requires_runtime]
    if not checks:
        print(f"dnetlint: no checks left to run (--select {args.select!r}"
              f"{' with --ast-only' if args.ast_only else ''}) — refusing "
              f"a green no-op", file=sys.stderr)
        return 2

    if args.diff is not None and args.write_baseline:
        # a diff run sees only the changed files' findings (and no
        # runtime passes); writing that partial set would silently
        # truncate every other file's grandfathered entries
        print("dnetlint: --write-baseline needs a full run; drop --diff",
              file=sys.stderr)
        return 2

    only_files = None
    if args.diff is not None:
        only_files = changed_files(REPO, args.diff)
        if only_files is None:
            print(
                f"dnetlint: git diff vs {args.diff!r} failed; falling back "
                f"to a full run", file=sys.stderr,
            )
        elif not only_files:
            print(f"dnetlint: no .py changes vs {args.diff} — nothing to lint")
            return 0

    baseline_path = (
        Path(args.baseline) if args.baseline else REPO / DEFAULT_BASELINE
    )
    report = run_analysis(
        REPO,
        checks=checks,
        include_runtime=not (args.ast_only or args.diff is not None),
        baseline_path=baseline_path,
        ignore_baseline=args.write_baseline,
        only_files=only_files,
    )

    if args.write_baseline:
        write_baseline(baseline_path, report.findings)
        print(f"dnetlint: wrote {len(report.findings)} entries to "
              f"{baseline_path} — add a justification per line")
        return 0

    if not args.quiet:
        for f in report.findings:
            print(f.render())
    if args.json is not None:
        out = (
            next_report_path(REPO) if args.json == "auto" else Path(args.json)
        )
        # merge the runtime-sanitizer section: DS catalog + any findings a
        # DNET_SAN=1 run persisted (DNET_SAN_REPORT / .dsan-findings.json)
        from dnet_tpu.analysis.runtime import runtime_section

        write_report_json(report, out, extra={"runtime": runtime_section(REPO)})
        if not args.quiet:
            print(f"dnetlint: report written to {out}")
    scope = (
        f" ({len(only_files)} changed file(s) vs {args.diff})"
        if only_files is not None else ""
    )
    summary = (
        f"dnetlint: {len(report.findings)} finding(s) "
        f"({len(report.baselined)} baselined, {report.suppressed} "
        f"suppressed) over {report.files_scanned} files{scope}, "
        f"{len(report.checks_run)} checks"
    )
    print(summary)
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
