#!/usr/bin/env python
"""bench_compare: diff two BENCH_SERVE_r*.json records, gate regressions.

Usage::

    python scripts/bench_compare.py OLD.json NEW.json
    python scripts/bench_compare.py OLD.json NEW.json --leg pipelined
    python scripts/bench_compare.py OLD.json NEW.json \
        --fail-on goodput.tok_s=-5% \
        --fail-on latency_ms.e2e.p95_ms=+10%
    python scripts/bench_compare.py OLD.json NEW.json --json

Thresholds are DIRECTIONAL (dnet_tpu/loadgen/compare.py): the sign names
the bad direction — ``+10%`` fails on a rise past 10% (latencies, shed),
``-5%`` fails on a fall past 5% (goodput, availability); drop the ``%``
for absolute limits.  Exit status: 0 clean, 1 any gate violated, 2 usage
errors (unreadable record, bad spec, no matching legs).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dnet_tpu.loadgen.compare import (  # noqa: E402
    compare_records,
    parse_fail_rule,
)


def _load(path: str) -> dict:
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        raise SystemExit(f"cannot read {path}: {exc}")
    if not isinstance(record, dict):
        raise SystemExit(f"{path}: not a JSON object")
    return record


def _fmt(entry: dict) -> str:
    rel = f" ({entry['rel'] * 100:+.1f}%)" if "rel" in entry else ""
    return f"{entry['old']:g} -> {entry['new']:g}  [{entry['delta']:+g}]{rel}"


def _print_text(result: dict, old_path: str, new_path: str) -> None:
    print(f"bench_compare: {old_path} -> {new_path}")
    for name, d in result["legs"].items():
        print(f"\n== leg: {name} ==")
        for path, entry in d["metrics"].items():
            print(f"  {path:32s} {_fmt(entry)}")
        for section in ("shed_by_reason", "phase_mean_ms",
                        "critical_path_mean_ms", "dominant"):
            block = d.get(section)
            if not block:
                continue
            print(f"  -- {section} --")
            for key, entry in block.items():
                print(f"  {key:32s} {_fmt(entry)}")
    for name in result["unmatched_old"]:
        print(f"\nleg {name!r} only in OLD record (skipped)")
    for name in result["unmatched_new"]:
        print(f"\nleg {name!r} only in NEW record (skipped)")
    if result["violations"]:
        print("\nREGRESSIONS:")
        for v in result["violations"]:
            print(f"  FAIL {v}")
    elif result["legs"]:
        print("\nok: no gated regressions")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_compare", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("old", help="baseline BENCH_SERVE_r*.json")
    ap.add_argument("new", help="candidate BENCH_SERVE_r*.json")
    ap.add_argument(
        "--leg", default=None,
        help="compare one named leg only (multi-leg records)",
    )
    ap.add_argument(
        "--fail-on", action="append", default=[], metavar="PATH=LIMIT",
        help="regression gate, e.g. goodput.tok_s=-5% or "
             "latency_ms.ttft.p95_ms=+10%% (repeatable)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the structured comparison instead of text",
    )
    args = ap.parse_args(argv)

    try:
        rules = tuple(parse_fail_rule(s) for s in args.fail_on)
    except ValueError as exc:
        ap.error(str(exc))
    old, new = _load(args.old), _load(args.new)
    try:
        result = compare_records(old, new, rules=rules, leg=args.leg)
    except ValueError as exc:
        raise SystemExit(str(exc))
    if not result["legs"]:
        raise SystemExit(
            "no comparable legs shared by the two records "
            f"(old: {result['unmatched_old']}, new: {result['unmatched_new']})"
        )
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        _print_text(result, args.old, args.new)
    return 1 if result["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
