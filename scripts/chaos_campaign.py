#!/usr/bin/env python
"""Run the deterministic chaos campaign and emit CHAOS_r<NN>.json.

    # the tier-1-friendly slice (<= 8 cells, fast scenarios only)
    python scripts/chaos_campaign.py --smoke

    # the full matrix against a real checkpoint, recorded as round 2
    python scripts/chaos_campaign.py --model /path/to/ckpt --round 2

    # replay one cell from a record's repro string
    DNET_CHAOS='admit:error_at:3+5' DNET_CHAOS_SEED=4242 \
        python scripts/chaos_campaign.py --cell 'local:admit:error_at'

Without --model a random-weight tiny Llama checkpoint is generated in a
temp dir (same fixture tier-1 uses), so the campaign runs anywhere the
test suite does.  Exit status: 0 when every cell is green, 1 on any
invariant violation, 2 on operator error.

Note the DNET_CHAOS/DNET_CHAOS_SEED env vars in a repro string are
informational — the campaign installs each cell's spec itself from the
matrix, so `--seed N --cell ID` alone reproduces the cell bit-for-bit.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DNET_OBS_ENABLED", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="deterministic chaos campaign over the fault matrix"
    )
    ap.add_argument("--model", default="", help="checkpoint dir (default: generated tiny llama)")
    ap.add_argument("--seed", type=int, default=0, help="campaign seed (fixes the whole schedule)")
    ap.add_argument("--round", type=int, default=1, dest="round_no", help="record number for CHAOS_r<NN>.json")
    ap.add_argument("--smoke", action="store_true", help="run the <=8-cell smoke slice")
    ap.add_argument("--cell", action="append", default=[], help="run only this cell id (repeatable)")
    ap.add_argument("--list", action="store_true", help="print the cell schedule and exit")
    ap.add_argument("--out", default="", help="output path (default CHAOS_r<NN>.json)")
    args = ap.parse_args()

    from dnet_tpu.chaos.campaign import build_matrix, run_campaign, select_cells, write_record

    if args.list:
        for cell in select_cells(build_matrix(args.seed), only=args.cell or None, smoke=args.smoke):
            print(f"{cell.cell_id:44s} {cell.chaos_spec}")
        return 0

    tmp = None
    model_dir = args.model
    if not model_dir:
        from tests.fakes.checkpoints import make_tiny_llama

        tmp = tempfile.TemporaryDirectory(prefix="dnet-chaos-")
        model_dir = tmp.name
        make_tiny_llama(model_dir)
        print(f"generated tiny llama checkpoint at {model_dir}")

    try:
        record = asyncio.run(run_campaign(
            model_dir,
            seed=args.seed,
            only=args.cell or None,
            smoke=args.smoke,
            round_no=args.round_no,
        ))
    finally:
        if tmp is not None:
            tmp.cleanup()

    out = args.out or f"CHAOS_r{args.round_no:02d}.json"
    write_record(record, out)
    s = record["summary"]
    print(
        f"chaos campaign: {record['matrix']['cells_run']} cells, "
        f"{s['ok']} ok, {s['violations']} violations, "
        f"{s['http_500']} http 500s, {s['duration_s']}s -> {out}"
    )
    for cell in record["cells"]:
        if cell["violations"]:
            print(f"  FAIL {cell['cell']}")
            for v in cell["violations"]:
                print(f"       [{v['family']}] {v['detail']}")
            print(f"       repro: {cell['repro']}")
    return 0 if s["violations"] == 0 else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        sys.exit(2)
