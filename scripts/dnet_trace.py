#!/usr/bin/env python
"""dnet_trace: fetch a Perfetto trace dump from a running API node.

Usage::

    python scripts/dnet_trace.py chatcmpl-abc123          # one request
    python scripts/dnet_trace.py chatcmpl-abc123 --cluster # stitch shards
    python scripts/dnet_trace.py --last-s 60               # serving window
    python scripts/dnet_trace.py --last-s 60 -o window.json

Writes Chrome trace-event / Perfetto JSON (api/http.py /v1/debug/trace
routes, rendered by obs/trace.py) — open the file at ui.perfetto.dev or
chrome://tracing.  Default output: ``dnet_trace_<rid>.json`` or
``dnet_trace_window.json``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dnet_trace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "rid", nargs="?", default=None,
        help="request id (chatcmpl-... / cmpl-...); omit with --last-s",
    )
    ap.add_argument(
        "--base-url", default="http://127.0.0.1:8000",
        help="API node base URL (default %(default)s)",
    )
    ap.add_argument(
        "--cluster", action="store_true",
        help="stitch every shard's spans into the trace (rid mode)",
    )
    ap.add_argument(
        "--last-s", type=float, default=None,
        help="serving-window dump: every retained request of the last N s",
    )
    ap.add_argument(
        "-o", "--output", default=None,
        help="output path (default dnet_trace_<rid|window>.json)",
    )
    ap.add_argument(
        "--timeout", type=float, default=30.0,
        help="HTTP timeout seconds (default %(default)s)",
    )
    args = ap.parse_args(argv)
    if (args.rid is None) == (args.last_s is None):
        ap.error("give exactly one of: a rid, or --last-s N")

    import httpx

    if args.rid is not None:
        url = f"{args.base_url}/v1/debug/trace/{args.rid}"
        params = {"cluster": "1"} if args.cluster else {}
        default_out = f"dnet_trace_{args.rid}.json"
    else:
        url = f"{args.base_url}/v1/debug/trace"
        params = {"last_s": str(args.last_s)}
        default_out = "dnet_trace_window.json"

    try:
        resp = httpx.get(url, params=params, timeout=args.timeout)
    except httpx.HTTPError as exc:
        raise SystemExit(f"fetch failed: {exc}")
    if resp.status_code == 404:
        raise SystemExit(
            f"no recorded timeline for {args.rid!r} — the flight recorder "
            "keeps only recent requests (is DNET_OBS_ENABLED on?)"
        )
    if resp.status_code != 200:
        raise SystemExit(f"HTTP {resp.status_code}: {resp.text[:200]}")
    trace = resp.json()
    n = len(trace.get("traceEvents", []))
    out_path = Path(args.output or default_out)
    out_path.write_text(json.dumps(trace))
    other = trace.get("otherData", {})
    print(
        f"wrote {out_path} ({n} events, "
        f"{other.get('timelines', '?')} timeline(s), "
        f"{other.get('tick_records', '?')} tick record(s))"
    )
    if other.get("truncated_events"):
        print(
            f"warning: {other['truncated_events']} events truncated "
            "(raise DNET_OBS_TRACE_MAX_EVENTS)"
        )
    print("open at https://ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
