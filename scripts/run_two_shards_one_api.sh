#!/usr/bin/env bash
# Local 2-shard + 1-API ring on one machine (reference:
# scripts/run_two_shards_one_api.sh — manual topology split across shards).
#
# Usage: scripts/run_two_shards_one_api.sh /path/to/model [layer_split]
set -euo pipefail

MODEL="${1:?usage: $0 /path/to/model [split_layer]}"
SPLIT="${2:-}"
HERE="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$HERE"

S0_HTTP=8081; S0_GRPC=58081
S1_HTTP=8082; S1_GRPC=58082
API_HTTP=8080; API_GRPC=58080

NUM_LAYERS=$(python - "$MODEL" <<'EOF'
import json, sys, pathlib
print(json.loads((pathlib.Path(sys.argv[1]) / "config.json").read_text())["num_hidden_layers"])
EOF
)
SPLIT="${SPLIT:-$((NUM_LAYERS / 2))}"
echo ">> $NUM_LAYERS layers; shard0 = [0..$((SPLIT-1))], shard1 = [$SPLIT..$((NUM_LAYERS-1))]"

HOSTFILE="$(mktemp)"
cat > "$HOSTFILE" <<EOF
s0 127.0.0.1 $S0_HTTP $S0_GRPC
s1 127.0.0.1 $S1_HTTP $S1_GRPC
EOF

cleanup() { kill 0 2>/dev/null || true; }
trap cleanup EXIT

python -m dnet_tpu.cli.shard --host 127.0.0.1 --http-port $S0_HTTP --grpc-port $S0_GRPC \
    --shard-name s0 --discovery none &
python -m dnet_tpu.cli.shard --host 127.0.0.1 --http-port $S1_HTTP --grpc-port $S1_GRPC \
    --shard-name s1 --discovery none &
python -m dnet_tpu.cli.api --host 127.0.0.1 --http-port $API_HTTP --grpc-port $API_GRPC \
    --hostfile "$HOSTFILE" &

for port in $S0_HTTP $S1_HTTP $API_HTTP; do
  until curl -sf "http://127.0.0.1:$port/health" > /dev/null; do sleep 0.5; done
done
echo ">> all nodes healthy"

LAYERS0=$(python -c "print(list(range(0, $SPLIT)))")
LAYERS1=$(python -c "print(list(range($SPLIT, $NUM_LAYERS)))")
curl -sf -X POST "http://127.0.0.1:$API_HTTP/v1/prepare_topology_manual" \
  -H 'Content-Type: application/json' \
  -d "{\"model\": \"$MODEL\", \"assignments\": [
        {\"instance\": \"s0\", \"layers\": $LAYERS0},
        {\"instance\": \"s1\", \"layers\": $LAYERS1}]}" | python -m json.tool
curl -sf -X POST "http://127.0.0.1:$API_HTTP/v1/load_model" \
  -H 'Content-Type: application/json' -d "{\"model\": \"$MODEL\"}" | python -m json.tool

echo ">> ring is serving; try:"
echo "curl -s http://127.0.0.1:$API_HTTP/v1/chat/completions -H 'Content-Type: application/json' \\"
echo "  -d '{\"model\":\"$MODEL\",\"messages\":[{\"role\":\"user\",\"content\":\"Hello\"}],\"max_tokens\":64}'"
wait
