#!/usr/bin/env python
"""Fetch a model into the local models dir (reference: scripts/download_model.py).

Zero-egress deployments skip this entirely: point DNET_API_MODELS_DIR /
DNET_SHARD_MODELS_DIR at a directory that already holds HF-format model
folders (config.json + *.safetensors [+ tokenizer files]).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("repo_id", help="HF repo id, e.g. meta-llama/Llama-3.2-1B-Instruct")
    p.add_argument("--models-dir", default="~/.dnet-tpu/models")
    args = p.parse_args()

    dest = Path(args.models_dir).expanduser() / args.repo_id.replace("/", "--")
    try:
        from huggingface_hub import snapshot_download
    except ImportError:
        print(
            "huggingface_hub not installed (zero-egress image?). Place the "
            f"model manually at {dest}",
            file=sys.stderr,
        )
        return 1
    dest.parent.mkdir(parents=True, exist_ok=True)
    path = snapshot_download(
        args.repo_id,
        local_dir=dest,
        allow_patterns=["*.safetensors*", "*.json", "tokenizer*", "*.model"],
    )
    print(f"downloaded to {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
