#!/usr/bin/env python
"""Pre-repack a model's layers for fast weight streaming.

Reference: scripts/repack_windows.py — warms the per-layer repack cache
(mapped, transposed, dtype-cast arrays) so offload-mode shard startup skips
the mapping work.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("model_dir")
    p.add_argument("--layers", default="", help="comma list / a:b range; default all")
    p.add_argument("--param-dtype", default="bfloat16")
    p.add_argument("--repack-dir", default="~/.dnet-tpu/repacked")
    p.add_argument(
        "--weight-quant-bits", type=int, default=0, choices=[0, 4, 8],
        help="pre-quantize layers (must match the serving setting: the "
        "repack cache key embeds it)",
    )
    args = p.parse_args()

    from dnet_tpu.core.weights import HostLayerStore
    from dnet_tpu.models import ModelConfig, get_ring_model_cls
    from dnet_tpu.utils.checkpoint import Checkpoint

    ckpt = Checkpoint(args.model_dir)
    cfg = ModelConfig.from_hf(ckpt.config)
    if args.layers:
        if ":" in args.layers:
            a, b = args.layers.split(":")
            layers = list(range(int(a), int(b)))
        else:
            layers = [int(x) for x in args.layers.split(",")]
    else:
        layers = list(range(cfg.num_hidden_layers))

    model = get_ring_model_cls(cfg.model_type)(cfg, layers)
    store = HostLayerStore(
        ckpt,
        model,
        param_dtype=args.param_dtype,
        repack_dir=args.repack_dir,
        weight_quant_bits=args.weight_quant_bits,
    )
    t0 = time.perf_counter()
    for i, layer in enumerate(layers):
        store.layer_host(layer)
        store.drop_host(layer)
        print(f"\r[{i + 1}/{len(layers)}] layer {layer}", end="", flush=True)
    print(f"\nrepacked {len(layers)} layers into {store.repack_path} "
          f"in {time.perf_counter() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
