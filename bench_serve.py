#!/usr/bin/env python
"""Serving-grade load bench: open-loop traffic -> BENCH_SERVE_*.json.

The served-throughput gate ROADMAP item 5(b) calls for: where bench.py
measures one stream's device rate, this drives a SEEDED open-loop arrival
process of concurrent OpenAI-API streaming clients (dnet_tpu/loadgen/) and
reports what serving actually delivered — goodput over completed requests
only, TTFT/TPOT/E2E p50/p95/p99, the shed-rate breakdown by status and
admission reason, SLO attainment cross-validated against the live
`dnet_slo_*` gauges, and the decode-step phase / JIT-compile attribution
that says WHERE the time went.

Two targets:

- default: an IN-PROCESS single-node server over `--model` (CPU or
  whatever backend jax resolves) — the tier-1-reproducible smoke shape;
- `--base-url http://api:8080`: any live deployment, including a real
  multi-shard ring (the bench is then a pure client; phase attribution
  reflects whatever the target's /metrics expose).

Every knob also rides DNET_LOADGEN_* (config.LoadgenSettings); CLI flags
win.  The report lands in BENCH_SERVE_r<NN>.json (next free index) unless
--out names a path.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import re
import socket
import sys
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="bench_serve", description=__doc__)
    p.add_argument("--model", default="",
                   help="checkpoint dir or catalog id (in-process mode); "
                   "for --base-url, the model name to put in request bodies")
    p.add_argument("--base-url", default="",
                   help="drive a live server instead of serving in-process")
    p.add_argument("--requests", type=int, default=None)
    p.add_argument("--rate", type=float, default=None, dest="rate_rps",
                   help="mean arrival rate (requests/s)")
    p.add_argument("--arrival", choices=["poisson", "fixed"], default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--buckets", default=None,
                   help="prompt:max_tokens,... length classes")
    p.add_argument("--weights", default=None, help="bucket weights")
    p.add_argument("--temperature", type=float, default=None)
    p.add_argument("--warmup-s", type=float, default=None,
                   help="exclude requests scheduled before this offset")
    p.add_argument("--timeout-s", type=float, default=None)
    p.add_argument("--slots", type=int, default=4,
                   help="in-process: continuous-batching slots (1 = local)")
    p.add_argument("--sched", action="store_true",
                   help="in-process: serve through the iteration-level "
                   "scheduler (DNET_SCHED=1, dnet_tpu/sched/) instead of "
                   "the legacy kick-coalescing engine path")
    p.add_argument("--ring-tp", action="store_true",
                   help="drive the workload over the in-process two-shard "
                   "ring THREE times — tp=1 baseline (r04's pipelined wire "
                   "config), tensor-parallel lossless, and q8 quantized "
                   "collectives — and emit one composite report with "
                   "meta.tp and collective-byte books per leg "
                   "(parallel/tp.py)")
    p.add_argument("--ring-inproc", action="store_true",
                   help="drive the workload over an in-process two-shard "
                   "ring TWICE — legacy serial wire vs the overlapped "
                   "qsparse8 pipeline (DNET_WIRE_PIPELINE=1) — and emit "
                   "one composite report with per-hop tx bytes and "
                   "encode/decode attribution (loadgen/ring_harness.py)")
    p.add_argument("--wire-pct", type=float, default=0.75,
                   help="ring-inproc: qsparse8 column-drop fraction for "
                   "the pipelined leg (DNET_WIRE_QSPARSE_PCT)")
    p.add_argument("--tp", type=int, default=0,
                   help="in-process ring legs: NamedSharding tensor-"
                   "parallel degree per shard (parallel/tp.py; 0 = the "
                   "DNET_TP default, 1 = single-chip).  Forced-host CPU "
                   "devices emulate the chips under tier-1.")
    p.add_argument("--tp-collective", default="",
                   help="ring-inproc: TP collective mode for every shard "
                   "(auto|lossless|q8; '' = DNET_TP_COLLECTIVE default)")
    p.add_argument("--fleet", type=int, default=0,
                   help="drive the workload through the fleet front door "
                   "(dnet_tpu/fleet/) THREE times — 1 replica, N replicas "
                   "behind the least-loaded prefix-affine router, and the "
                   "failover drill (kill r1 mid-burst; zero 5xx is the "
                   "bar) — and emit one composite report with per-replica "
                   "goodput and routing counters per leg")
    p.add_argument("--fleet-pace-ms", type=float, default=40.0,
                   help="fleet legs: emulated device-bound decode floor "
                   "(DNET_FLEET_DECODE_PACE_MS).  On a real TPU ring the "
                   "host WAITS on the device, so replicas scale across "
                   "hosts; co-hosted CPU replicas would just contend for "
                   "the same cores and show no scaling.  0 disables the "
                   "floor (raw CPU contention).")
    p.add_argument("--max-seq", type=int, default=1024)
    p.add_argument("--param-dtype", default="bfloat16")
    p.add_argument("--out", default="", help="report path (default: next "
                   "BENCH_SERVE_r<NN>.json)")
    p.add_argument("--no-rows", action="store_true",
                   help="omit per-request rows from the report")
    return p


def _spec_from(args):
    from dnet_tpu.config import get_settings
    from dnet_tpu.loadgen import WorkloadSpec, parse_buckets

    s = get_settings().loadgen

    def pick(cli, env):
        return env if cli is None else cli

    return WorkloadSpec(
        seed=pick(args.seed, s.seed),
        requests=pick(args.requests, s.requests),
        rate_rps=pick(args.rate_rps, s.rate_rps),
        arrival=pick(args.arrival, s.arrival),
        buckets=parse_buckets(
            pick(args.buckets, s.buckets), pick(args.weights, s.weights)
        ),
        temperature=pick(args.temperature, s.temperature),
        warmup_s=pick(args.warmup_s, s.warmup_s),
        timeout_s=pick(args.timeout_s, s.timeout_s),
    )


def _next_report_path() -> Path:
    used = set()
    for f in Path(".").glob("BENCH_SERVE_r*.json"):
        m = re.match(r"BENCH_SERVE_r(\d+)\.json$", f.name)
        if m:
            used.add(int(m.group(1)))
    n = 1
    while n in used:
        n += 1
    return Path(f"BENCH_SERVE_r{n:02d}.json")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _kv_mode(engine) -> str:
    """The KV serving mode the loaded engine RESOLVED to (not just what
    the env asked for — a ragged refusal falls back to paged gather, and
    the stamp must say which path the numbers measured)."""
    if getattr(engine, "kv_ragged", False):
        return "ragged"
    if getattr(engine, "kv_pool", None) is not None:
        return "paged"
    return "dense"


def _tp_mode(engine) -> dict:
    """meta.tp: the RESOLVED tensor-parallel shape of one engine (the
    meta.kv discipline — a clamped DNET_TP must stamp what actually
    served).  degree 1 = the pre-TP single-chip behavior."""
    from dnet_tpu.parallel.tp import TpEngine

    if isinstance(engine, TpEngine):
        return {"degree": engine.tp, "collective": engine.collective_mode}
    return {"degree": 1, "collective": "lossless"}


async def _run_remote(args, spec) -> dict:
    import aiohttp

    from dnet_tpu.loadgen import run_load

    # no session-level cap: the per-request budget (spec.timeout_s via
    # run_request's wait_for) owns the timeout; aiohttp's default
    # ClientTimeout(total=300) would silently override longer budgets
    async with aiohttp.ClientSession(
        base_url=args.base_url, timeout=aiohttp.ClientTimeout(total=None)
    ) as session:
        result = await run_load(
            session, spec, args.model or "default",
            include_rows=not args.no_rows,
            meta={"target": args.base_url, "mode": "remote"},
        )
    return result.report


async def _run_inprocess(args, spec) -> dict:
    """Single-node serving stack in this process (the bench.py-measured
    engines behind the REAL admission/SSE/driver path), driven over a
    loopback HTTP port so the client half is identical to remote mode."""
    import aiohttp

    from dnet_tpu.api.http import ApiHTTPServer
    from dnet_tpu.api.inference import InferenceManager
    from dnet_tpu.api.model_manager import LocalModelManager
    from dnet_tpu.config import get_settings
    from dnet_tpu.loadgen import run_load

    api = get_settings().api
    # legacy path: admission must not out-admit the engine's slot pool —
    # excess load then queues (and sheds with Retry-After) at the admission
    # layer instead of hard-failing against the batch-slot pool.  The
    # scheduler path queues and preempts INTERNALLY (WAITING is a real
    # state, admission is a function of free KV blocks), so it keeps the
    # configured concurrency and lets the tick loop do the pacing.
    max_concurrent = (
        api.max_concurrent_requests
        if args.sched
        else min(api.max_concurrent_requests, max(args.slots, 1))
    )
    inference = InferenceManager(
        adapter=None,
        request_timeout_s=api.request_timeout_s,
        max_concurrent=max_concurrent,
    )
    manager = LocalModelManager(
        inference,
        models_dir=api.models_dir,
        max_seq=args.max_seq,
        param_dtype=args.param_dtype,
        batch_slots=args.slots,
    )
    await manager.load_model(args.model, max_seq=args.max_seq)
    server = ApiHTTPServer(inference, manager)
    port = _free_port()
    await server.start("127.0.0.1", port)
    try:
        async with aiohttp.ClientSession(
            base_url=f"http://127.0.0.1:{port}",
            # per-request wait_for owns the budget (see remote mode)
            timeout=aiohttp.ClientTimeout(total=None),
        ) as session:
            result = await run_load(
                session, spec, args.model,
                include_rows=not args.no_rows,
                meta={
                    "mode": "in-process",
                    "engine": "sched" if args.sched else "legacy",
                    "kv": _kv_mode(manager.engine),
                    "tp": _tp_mode(manager.engine),
                    "slots": args.slots,
                    "max_seq": args.max_seq,
                    "param_dtype": args.param_dtype,
                },
            )
    finally:
        await server.stop()
        await manager.unload_model()
    return result.report


async def _ring_leg(args, spec, *, pipeline: bool, codec: str,
                    tp: int = None, tp_collective: str = None) -> dict:
    """One ring run: fresh two-shard in-process ring, fresh obs books,
    the full loadgen client over a real loopback HTTP port.  Returns the
    loadgen report extended with the harness's per-hop wire accounting
    and the overlap tracker's serial/hidden split."""
    import os

    import aiohttp

    from dnet_tpu.config import reset_settings_cache
    from dnet_tpu.loadgen import run_load
    from dnet_tpu.loadgen.ring_harness import InprocRing
    from dnet_tpu.obs import metric, reset_obs
    from dnet_tpu.transport.wire_pipeline import overlap

    if pipeline:
        os.environ["DNET_WIRE_PIPELINE"] = "1"
    else:
        os.environ.pop("DNET_WIRE_PIPELINE", None)
    os.environ["DNET_WIRE_QSPARSE_PCT"] = str(args.wire_pct)
    reset_settings_cache()
    reset_obs()
    overlap.reset()

    cfg = json.loads(
        (Path(args.model).expanduser() / "config.json").read_text()
    )
    n_layers = int(cfg["num_hidden_layers"])
    half = max(n_layers // 2, 1)
    ring = InprocRing(
        args.model,
        layers0=range(0, half),
        layers1=range(half, n_layers),
        max_seq=args.max_seq,
        param_dtype=args.param_dtype,
        wire_codec=codec,
        tp=args.tp if tp is None else tp,
        tp_collective=(
            args.tp_collective if tp_collective is None else tp_collective
        ),
    )
    await ring.start()
    port = _free_port()
    await ring.server.start("127.0.0.1", port)
    try:
        async with aiohttp.ClientSession(
            base_url=f"http://127.0.0.1:{port}",
            timeout=aiohttp.ClientTimeout(total=None),
        ) as session:
            result = await run_load(
                session, spec, "inproc-ring",
                include_rows=not args.no_rows,
                meta={
                    "mode": "ring-inproc",
                    "wire": "pipelined" if pipeline else "legacy",
                    "codec": codec,
                    "qsparse_pct": args.wire_pct if codec == "qsparse8" else None,
                    "shards": 2,
                    "layers": [list(ring.layers0), list(ring.layers1)],
                    # the RESOLVED per-shard TP shape (parallel/tp.py):
                    # what actually served, not what --tp asked for
                    "tp": _tp_mode(ring.s0.compute.engine),
                    "max_seq": args.max_seq,
                    "param_dtype": args.param_dtype,
                },
            )
            # resolved TP shape, read while the engines are still alive
            # (ring.stop() frees them)
            tp_meta = _tp_mode(ring.s0.compute.engine)
    finally:
        await ring.server.stop()
        await ring.stop()
    report = result.report
    # TP collective books for this leg (obs was reset at leg start, so the
    # absolute values ARE the leg totals): the analytic per-dispatch
    # interconnect bytes plus the load-time latency probe medians
    coll_ms = metric("dnet_tp_collective_ms").labels(op="all_reduce")
    report["tp"] = {
        **tp_meta,
        "collective_bytes_all_reduce": metric(
            "dnet_tp_collective_bytes_total"
        ).labels(op="all_reduce").value,
        "collective_probe_ms_all_reduce": round(
            coll_ms.sum / coll_ms.count, 3
        ) if coll_ms.count else None,
    }
    wire = ring.stats.as_dict()
    ov = overlap.snapshot()
    hidden_frames = sum(wire["hidden_frames"].values()) or 1
    report["wire"] = {
        **wire,
        "encode_ms_count": metric("dnet_wire_encode_ms").count,
        "decode_ms_count": metric("dnet_wire_decode_ms").count,
        # THE overlap numbers: serial = codec ms paid on the compute
        # thread, hidden = codec ms overlapped with compute (tx stage /
        # ingress).  Per-hidden-frame serial ms ~0 is the acceptance bar.
        "codec_serial_ms": round(ov["serial_ms"], 3),
        "codec_hidden_ms": round(ov["hidden_ms"], 3),
        # compute-thread waits on the full encode ring: the depth bound
        # exerting backpressure (the wire IS the bottleneck on a toy-model
        # CPU ring), kept out of the serial/overlap books
        "codec_backpressure_stall_ms": round(ov["stall_ms"], 3),
        "codec_serial_ms_per_hidden_frame": round(
            ov["serial_ms"] / hidden_frames, 4
        ),
        "overlap_ratio": round(ov["ratio"], 4),
    }
    return report


async def _run_ring_inproc(args, spec) -> dict:
    """Legacy serial wire vs overlapped qsparse8 pipeline over the SAME
    seeded workload and the SAME two-shard in-process ring: one composite
    BENCH_SERVE record proving the wire got smaller AND free."""
    import os

    from dnet_tpu.config import reset_settings_cache

    # the ring serves B=1 per nonce through two compute threads — a 16rps
    # open-loop burst queues at admission rather than shedding, so every
    # leg completes 96/96 and the comparison is codec-only (recorded in
    # meta; the per-request budget still bounds every stream)
    admit_depth = str(spec.requests)
    admit_timeout = str(spec.timeout_s)
    os.environ["DNET_ADMIT_QUEUE_DEPTH"] = admit_depth
    os.environ["DNET_ADMIT_QUEUE_TIMEOUT_S"] = admit_timeout
    # three legs, one seeded workload: the status-quo wire, what the
    # qsparse8 codec would cost ON the serial path, and the pipeline
    # hiding it — the middle leg is what makes "serial codec time ~0" a
    # like-for-like claim instead of a lossless-vs-quantized pun
    try:
        legacy = await _ring_leg(args, spec, pipeline=False, codec="lossless")
        q8_serial = await _ring_leg(
            args, spec, pipeline=False, codec="qsparse8"
        )
        pipelined = await _ring_leg(args, spec, pipeline=True, codec="qsparse8")
    finally:
        # a failed leg must not leave bench-sized admission queues or the
        # wire overrides behind for whatever runs in this process next
        os.environ.pop("DNET_WIRE_PIPELINE", None)
        os.environ.pop("DNET_WIRE_QSPARSE_PCT", None)
        os.environ.pop("DNET_ADMIT_QUEUE_DEPTH", None)
        os.environ.pop("DNET_ADMIT_QUEUE_TIMEOUT_S", None)
        reset_settings_cache()
    lw, sw, pw = legacy["wire"], q8_serial["wire"], pipelined["wire"]
    l_hidden = sum(lw["hidden_bytes"].values())
    p_hidden = sum(pw["hidden_bytes"].values())
    sync_ms = sw["codec_serial_ms_per_hidden_frame"]
    piped_ms = pw["codec_serial_ms_per_hidden_frame"]
    return {
        "kind": "bench_serve_ring",
        "spec": legacy["spec"],
        "meta": {
            "mode": "ring-inproc",
            "model": args.model,
            "admit_queue_depth": admit_depth,
            "admit_queue_timeout_s": admit_timeout,
        },
        "legacy": legacy,
        "qsparse8_serial": q8_serial,
        "pipelined": pipelined,
        "comparison": {
            "hidden_hop_bytes_legacy": l_hidden,
            "hidden_hop_bytes_pipelined": p_hidden,
            "hidden_hop_bytes_ratio": round(l_hidden / max(p_hidden, 1), 2),
            # per-hidden-frame codec ms the COMPUTE THREAD paid
            "codec_serial_ms_per_frame_lossless": lw[
                "codec_serial_ms_per_hidden_frame"
            ],
            "codec_serial_ms_per_frame_qsparse8_serial": sync_ms,
            "codec_serial_ms_per_frame_qsparse8_pipelined": piped_ms,
            "serial_codec_hidden_fraction": round(
                1.0 - piped_ms / max(sync_ms, 1e-9), 4
            ),
            "overlap_ratio_pipelined": pw["overlap_ratio"],
            "goodput_tok_s_legacy": legacy["goodput"]["tok_s"],
            "goodput_tok_s_qsparse8_serial": q8_serial["goodput"]["tok_s"],
            "goodput_tok_s_pipelined": pipelined["goodput"]["tok_s"],
            "completed_legacy": legacy["requests"]["completed"],
            "completed_qsparse8_serial": q8_serial["requests"]["completed"],
            "completed_pipelined": pipelined["requests"]["completed"],
        },
    }


async def _fleet_leg(args, spec, n_replicas: int, *,
                     fail_after_s: float = None) -> dict:
    """One fleet run: N fresh single-node replicas (full InferenceManager
    + engine stacks over the SAME checkpoint), one FleetManager front
    door, one loopback HTTP port, fresh obs books.  `fail_after_s` arms
    the failover drill: a timer marks r1 dead mid-burst, and the router
    must re-admit its in-flight streams on a survivor with zero 5xx."""
    import os

    import aiohttp

    from dnet_tpu.api.http import ApiHTTPServer
    from dnet_tpu.api.inference import InferenceManager
    from dnet_tpu.api.model_manager import LocalModelManager
    from dnet_tpu.config import get_settings, reset_settings_cache
    from dnet_tpu.fleet import FleetManager
    from dnet_tpu.loadgen import run_load
    from dnet_tpu.obs import metric, reset_obs

    os.environ["DNET_FLEET"] = str(n_replicas)
    reset_settings_cache()
    reset_obs()
    api = get_settings().api
    replicas = []
    for _ in range(n_replicas):
        inference = InferenceManager(
            adapter=None,
            request_timeout_s=api.request_timeout_s,
            # legacy engine path: admission capacity == the slot pool
            # (see _run_inprocess)
            max_concurrent=min(
                api.max_concurrent_requests, max(args.slots, 1)
            ),
        )
        manager = LocalModelManager(
            inference,
            models_dir=api.models_dir,
            max_seq=args.max_seq,
            param_dtype=args.param_dtype,
            batch_slots=args.slots,
        )
        await manager.load_model(args.model, max_seq=args.max_seq)
        replicas.append((inference, manager))
    fleet = FleetManager()
    for i, (inference, _mgr) in enumerate(replicas):
        fleet.add_replica(f"r{i}", inference)
    server = ApiHTTPServer(replicas[0][0], replicas[0][1], fleet=fleet)
    port = _free_port()
    await server.start("127.0.0.1", port)
    killer = None
    if fail_after_s is not None:
        async def _kill() -> None:
            await asyncio.sleep(fail_after_s)
            fleet.fail_replica("r1")

        killer = asyncio.ensure_future(_kill())
    try:
        async with aiohttp.ClientSession(
            base_url=f"http://127.0.0.1:{port}",
            timeout=aiohttp.ClientTimeout(total=None),
        ) as session:
            result = await run_load(
                session, spec, args.model,
                include_rows=not args.no_rows,
                meta={
                    "mode": "fleet",
                    "replicas": n_replicas,
                    "failover_drill": fail_after_s is not None,
                    "slots": args.slots,
                    "max_seq": args.max_seq,
                    "param_dtype": args.param_dtype,
                },
            )
    finally:
        if killer is not None:
            killer.cancel()
        await server.stop()
        for _inf, mgr in replicas:
            await mgr.unload_model()
    report = result.report
    # leg-local routing books (obs was reset at leg start, so absolute
    # values ARE the leg totals) + the 5xx count the failover bar gates on
    report["fleet_leg"] = {
        "http_5xx": sum(
            1 for o in result.outcomes if 500 <= o.status < 600
        ),
        "failovers_total": int(metric("dnet_fleet_failovers_total").value),
        "affinity_hits_total": int(
            metric("dnet_fleet_affinity_hits_total").value
        ),
    }
    return report


async def _run_fleet(args, spec) -> dict:
    """Fleet front-door legs over the SAME seeded workload: one replica,
    N replicas behind the least-loaded prefix-affine router, then the
    mid-burst failover drill.

    Admission queues are pinned DEEP (every request queues rather than
    sheds, like the r04 ring legs), so each capacity leg drains the
    identical workload and the goodput ratio is pure serving-rate
    scaling: tokens over the wall-clock each fleet size needs to drain
    the burst.  Decode runs under the DNET_FLEET_DECODE_PACE_MS floor
    (--fleet-pace-ms): on real hardware the host waits on the device
    and replicas scale across hosts, so the floor — which overlaps
    across co-hosted replicas the way device time would — is what makes
    a single-box fleet bench measure routing, not CPU contention."""
    import os

    from dnet_tpu.config import reset_settings_cache

    n = max(args.fleet, 2)
    admit_depth = str(spec.requests)
    os.environ["DNET_ADMIT_QUEUE_DEPTH"] = admit_depth
    os.environ["DNET_ADMIT_QUEUE_TIMEOUT_S"] = str(spec.timeout_s)
    os.environ["DNET_FLEET_DECODE_PACE_MS"] = str(max(args.fleet_pace_ms, 0.0))
    try:
        one = await _fleet_leg(args, spec, 1)
        two = await _fleet_leg(args, spec, n)
        # kill r1 ~40% into the measured serving window of the healthy
        # N-replica leg: late enough that it holds in-flight streams,
        # early enough that the survivors serve meaningful post-failover
        # load before the burst drains
        two_serving = max(two["duration_s"] - spec.warmup_s, 0.0)
        fail_at = spec.warmup_s + 0.4 * two_serving
        failover = await _fleet_leg(args, spec, n, fail_after_s=fail_at)
    finally:
        for k in ("DNET_FLEET", "DNET_ADMIT_QUEUE_DEPTH",
                  "DNET_ADMIT_QUEUE_TIMEOUT_S", "DNET_FLEET_DECODE_PACE_MS"):
            os.environ.pop(k, None)
        reset_settings_cache()
    g1 = one["goodput"]["tok_s"]
    g2 = two["goodput"]["tok_s"]
    return {
        "kind": "bench_serve_fleet",
        "spec": one["spec"],
        "meta": {
            "mode": "fleet",
            "model": args.model,
            "replicas": n,
            "failover_at_s": round(fail_at, 3),
            "admit_queue_depth": admit_depth,
            "decode_pace_ms": max(args.fleet_pace_ms, 0.0),
        },
        "one_replica": one,
        "two_replica": two,
        "failover": failover,
        "comparison": {
            "goodput_tok_s_one": g1,
            "goodput_tok_s_two": g2,
            "goodput_ratio": round(g2 / max(g1, 1e-9), 3),
            "completed_one": one["requests"]["completed"],
            "completed_two": two["requests"]["completed"],
            "completed_failover": failover["requests"]["completed"],
            "ttft_p99_ms_one": one["latency_ms"]["ttft"]["p99_ms"],
            "ttft_p99_ms_two": two["latency_ms"]["ttft"]["p99_ms"],
            "tpot_p99_ms_one": one["latency_ms"]["tpot"]["p99_ms"],
            "tpot_p99_ms_two": two["latency_ms"]["tpot"]["p99_ms"],
            "failover_http_5xx": failover["fleet_leg"]["http_5xx"],
            "failovers_total": failover["fleet_leg"]["failovers_total"],
        },
    }


async def _run_ring_tp(args, spec) -> dict:
    """Hybrid TP x PP legs over the SAME seeded workload and the SAME
    two-shard in-process ring as r04: the tp=1 baseline (directly
    comparable to r04's pipelined leg — identical wire config), the
    tensor-parallel lossless leg (byte-identical streams, TP speedup
    bounded here by CPU chip emulation), and the q8 quantized-collective
    leg (strictly fewer interconnect bytes).  One composite record with
    meta.tp stamped per leg."""
    import os

    from dnet_tpu.config import reset_settings_cache

    tp = args.tp if args.tp > 0 else 4  # 0 = unset; an explicit 1 is honored
    admit_depth = str(spec.requests)
    admit_timeout = str(spec.timeout_s)
    os.environ["DNET_ADMIT_QUEUE_DEPTH"] = admit_depth
    os.environ["DNET_ADMIT_QUEUE_TIMEOUT_S"] = admit_timeout
    try:
        base = await _ring_leg(
            args, spec, pipeline=True, codec="qsparse8", tp=1,
            tp_collective="lossless",
        )
        tp_lossless = await _ring_leg(
            args, spec, pipeline=True, codec="qsparse8", tp=tp,
            tp_collective="lossless",
        )
        tp_q8 = await _ring_leg(
            args, spec, pipeline=True, codec="qsparse8", tp=tp,
            tp_collective="q8",
        )
    finally:
        os.environ.pop("DNET_WIRE_PIPELINE", None)
        os.environ.pop("DNET_WIRE_QSPARSE_PCT", None)
        os.environ.pop("DNET_ADMIT_QUEUE_DEPTH", None)
        os.environ.pop("DNET_ADMIT_QUEUE_TIMEOUT_S", None)
        reset_settings_cache()
    return {
        "kind": "bench_serve_ring_tp",
        "spec": base["spec"],
        "meta": {
            "mode": "ring-tp",
            "model": args.model,
            "tp": tp,
            "admit_queue_depth": admit_depth,
            "admit_queue_timeout_s": admit_timeout,
        },
        "tp1": base,
        "tp_lossless": tp_lossless,
        "tp_q8": tp_q8,
        "comparison": {
            "goodput_tok_s_tp1": base["goodput"]["tok_s"],
            "goodput_tok_s_tp_lossless": tp_lossless["goodput"]["tok_s"],
            "goodput_tok_s_tp_q8": tp_q8["goodput"]["tok_s"],
            "completed_tp1": base["requests"]["completed"],
            "completed_tp_lossless": tp_lossless["requests"]["completed"],
            "completed_tp_q8": tp_q8["requests"]["completed"],
            "collective_bytes_lossless": tp_lossless["tp"][
                "collective_bytes_all_reduce"
            ],
            "collective_bytes_q8": tp_q8["tp"][
                "collective_bytes_all_reduce"
            ],
        },
    }


def _summarize_ring_tp(report: dict) -> str:
    c = report["comparison"]
    return "\n".join([
        f"ring tp legs (tp={report['meta']['tp']}): goodput "
        f"{c['goodput_tok_s_tp1']}/{c['goodput_tok_s_tp_lossless']}/"
        f"{c['goodput_tok_s_tp_q8']} tok/s (tp1/lossless/q8), completed "
        f"{c['completed_tp1']}/{c['completed_tp_lossless']}/"
        f"{c['completed_tp_q8']}",
        f"collective bytes: lossless {c['collective_bytes_lossless']:.0f} "
        f"-> q8 {c['collective_bytes_q8']:.0f}",
    ])


def _summarize_fleet(report: dict) -> str:
    c = report["comparison"]
    fo = report["failover"]["fleet_leg"]
    return "\n".join([
        f"fleet legs ({report['meta']['replicas']} replicas): goodput "
        f"{c['goodput_tok_s_one']} -> {c['goodput_tok_s_two']} tok/s "
        f"({c['goodput_ratio']}x), completed {c['completed_one']} -> "
        f"{c['completed_two']}",
        f"ttft p99 ms: {c['ttft_p99_ms_one']} -> {c['ttft_p99_ms_two']}; "
        f"tpot p99 ms: {c['tpot_p99_ms_one']} -> {c['tpot_p99_ms_two']}",
        f"failover drill: {c['completed_failover']} completed, "
        f"{fo['http_5xx']} HTTP 5xx, {fo['failovers_total']} failover(s)",
    ])


def _summarize_ring(report: dict) -> str:
    c = report["comparison"]
    return "\n".join([
        f"ring wire: {c['hidden_hop_bytes_legacy']} -> "
        f"{c['hidden_hop_bytes_pipelined']} hidden-hop bytes "
        f"({c['hidden_hop_bytes_ratio']}x fewer)",
        f"serial codec ms/frame: lossless "
        f"{c['codec_serial_ms_per_frame_lossless']}, qsparse8 serial "
        f"{c['codec_serial_ms_per_frame_qsparse8_serial']} -> pipelined "
        f"{c['codec_serial_ms_per_frame_qsparse8_pipelined']} "
        f"({c['serial_codec_hidden_fraction']:.0%} off the compute thread; "
        f"overlap {c['overlap_ratio_pipelined']})",
        f"completed: {c['completed_legacy']}/"
        f"{c['completed_qsparse8_serial']}/{c['completed_pipelined']} "
        f"(legacy/q8-serial/pipelined); goodput "
        f"{c['goodput_tok_s_legacy']}/{c['goodput_tok_s_qsparse8_serial']}/"
        f"{c['goodput_tok_s_pipelined']} tok/s",
    ])


def _summarize(report: dict) -> str:
    if report.get("kind") == "bench_serve_fleet":
        return _summarize_fleet(report)
    if report.get("kind") == "bench_serve_ring_tp":
        return _summarize_ring_tp(report)
    if report.get("kind") == "bench_serve_ring":
        return _summarize_ring(report)
    r = report["requests"]
    g = report["goodput"]
    lat = report["latency_ms"]
    lines = [
        f"requests: {r['completed']}/{r['measured']} completed, "
        f"{r['shed']} shed ({r['shed_by_status']}), {r['failed']} failed",
        f"goodput: {g['tok_s']} tok/s ({g['tokens_out']} tokens over "
        f"{report['measured_window_s']}s)",
        f"ttft ms p50/p95/p99: {lat['ttft']['p50_ms']}/"
        f"{lat['ttft']['p95_ms']}/{lat['ttft']['p99_ms']}",
        f"tpot ms p50/p95/p99: {lat['tpot']['p50_ms']}/"
        f"{lat['tpot']['p95_ms']}/{lat['tpot']['p99_ms']}",
    ]
    pa = report.get("phase_attribution")
    if pa and pa["decode_step"]["count"]:
        parts = ", ".join(
            f"{ph}={v['sum_ms']:.0f}ms" for ph, v in pa["phases"].items()
        )
        lines.append(f"decode phases: {parts} (coverage {pa['coverage']})")
    slo = report.get("slo")
    if slo:
        lines.append(
            f"slo attained: {slo['attained']} (burning: {slo['burning']})"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    import os

    # honest attribution needs the obs fences; the bench opts in for its
    # own process (a remote target keeps its own setting)
    os.environ.setdefault("DNET_OBS_ENABLED", "1")
    args = build_parser().parse_args(argv)
    if not args.base_url and not args.model:
        print("error: --model is required without --base-url",
              file=sys.stderr)
        return 2
    if args.sched:
        if args.base_url:
            print("error: --sched is an in-process knob; a remote target "
                  "picks its own engine via DNET_SCHED", file=sys.stderr)
            return 2
        # before reset_settings_cache so SchedSettings sees it too
        os.environ["DNET_SCHED"] = "1"
        # --slots governs the lane count on BOTH paths (apples-to-apples:
        # DNET_SCHED_SLOTS=0 would widen the scheduler to max(slots, 8));
        # an explicit DNET_SCHED_SLOTS in the environment still wins
        os.environ.setdefault("DNET_SCHED_SLOTS", str(max(args.slots, 1)))
    from dnet_tpu.config import reset_settings_cache

    reset_settings_cache()
    spec = _spec_from(args)
    if args.fleet:
        if args.base_url:
            print("error: --fleet is an in-process mode", file=sys.stderr)
            return 2
        runner = _run_fleet
    elif args.ring_inproc or args.ring_tp:
        if args.base_url:
            print("error: --ring-inproc/--ring-tp are in-process modes",
                  file=sys.stderr)
            return 2
        runner = _run_ring_tp if args.ring_tp else _run_ring_inproc
    else:
        runner = _run_remote if args.base_url else _run_inprocess
    report = asyncio.run(runner(args, spec))
    out = Path(args.out) if args.out else _next_report_path()
    out.write_text(json.dumps(report, indent=2, sort_keys=True) + "\n")
    print(_summarize(report))
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
